"""Runtime kinds — the ``run:`` section of a component.

Parity targets (SURVEY.md §2 "Runtime kinds"): ``V1Job``, ``V1Service``,
``V1Dag``, ``V1Tuner``, ``V1Notifier``, ``V1CleanerJob`` and the distributed
kinds ``V1TFJob``/``V1PytorchJob``/``V1MPIJob``/``V1MXJob``/``V1XGBoostJob``/
``V1PaddleJob``/``V1DaskJob``/``V1RayJob`` (upstream delegates these to
Kubeflow CRDs + NCCL).  New, TPU-native kinds per the north star
(BASELINE.json): ``V1TPUJob``/``V1JaxJob`` — a slice topology + a parallelism
spec over a JAX device mesh; rendezvous is jax.distributed/XLA coordinator
env, collectives ride ICI, and no NCCL exists anywhere.
"""

from __future__ import annotations

from typing import Annotated, Any, Literal, Optional, Union

from pydantic import Field, field_validator, model_validator

from .base import BaseSchema
from .io import V1Param
from .k8s import V1Container
from .lifecycle import V1Environment
from .tpu import ACCELERATOR_SPECS, SliceTopology


class V1RunKind:
    JOB = "job"
    SERVICE = "service"
    DAG = "dag"
    TUNER = "tuner"
    NOTIFIER = "notifier"
    CLEANER = "cleaner"
    WATCHDOG = "watchdog"
    TFJOB = "tfjob"
    PYTORCHJOB = "pytorchjob"
    MPIJOB = "mpijob"
    MXJOB = "mxjob"
    XGBJOB = "xgbjob"
    PADDLEJOB = "paddlejob"
    DASKJOB = "daskjob"
    RAYJOB = "rayjob"
    TPUJOB = "tpujob"
    JAXJOB = "jaxjob"

    DISTRIBUTED = {TFJOB, PYTORCHJOB, MPIJOB, MXJOB, XGBJOB, PADDLEJOB, DASKJOB, RAYJOB, TPUJOB, JAXJOB}
    ALL = {
        JOB, SERVICE, DAG, TUNER, NOTIFIER, CLEANER, WATCHDOG,
        TFJOB, PYTORCHJOB, MPIJOB, MXJOB, XGBJOB, PADDLEJOB, DASKJOB, RAYJOB,
        TPUJOB, JAXJOB,
    }


class V1Init(BaseSchema):
    """One init step: fetch code/artifacts/files before the main container
    (upstream ``V1Init``; executed by the init runtime, SURVEY.md §2)."""

    artifacts: Optional[dict[str, Any]] = None  # {files:[], dirs:[], workers:int}
    paths: Optional[list[Any]] = None
    git: Optional[dict[str, Any]] = None  # {url, revision, flags}
    dockerfile: Optional[dict[str, Any]] = None
    file: Optional[dict[str, Any]] = None
    tensorboard: Optional[dict[str, Any]] = None
    lineage_ref: Optional[str] = None
    model_ref: Optional[str] = None
    artifact_ref: Optional[str] = None
    connection: Optional[str] = None
    path: Optional[str] = None
    container: Optional[V1Container] = None


class _BaseRun(BaseSchema):
    environment: Optional[V1Environment] = None
    connections: Optional[list[str]] = None
    volumes: Optional[list[dict[str, Any]]] = None


class V1Job(_BaseRun):
    """Batch job: init steps + one main container (upstream ``V1Job``)."""

    kind: Literal["job"] = "job"
    init: Optional[list[V1Init]] = None
    sidecars: Optional[list[V1Container]] = None
    container: Optional[V1Container] = None


class V1Service(_BaseRun):
    """Long-running service with exposed ports (upstream ``V1Service``).

    ``runtime`` (ISSUE 9) is the serving twin of the tpujob training
    shortcut: instead of a user container, replicas run the built-in
    online-inference runtime (paged KV cache + continuous batching +
    ``/generate``; serve/runtime.py) with this dict as its spec —
    {model, checkpoint, max_slots, block_size, prefill_chunk, port, ...}.

    ``autoscale`` closes the traffic loop: the agent scales the replica
    count from the run's own heartbeat-fed traffic gauges —
    {min_replicas, max_replicas, target_per_replica (concurrent
    running+waiting requests one replica should absorb, default
    max_slots), scale_down_after_s (sustained-low-traffic hysteresis,
    default 10)} — chip-budget-aware, through the launch-intent
    machinery."""

    kind: Literal["service"] = "service"
    init: Optional[list[V1Init]] = None
    sidecars: Optional[list[V1Container]] = None
    container: Optional[V1Container] = None
    ports: Optional[list[int]] = None
    rewrite_path: Optional[bool] = None
    is_external: Optional[bool] = None
    replicas: Optional[int] = None
    # Serving-runtime shortcut: run the built-in inference engine
    runtime: Optional[dict[str, Any]] = None
    # Traffic-driven replica autoscaling (agent-side control loop)
    autoscale: Optional[dict[str, Any]] = None


class V1KFReplica(BaseSchema):
    """A replica group in a Kubeflow-style distributed job (upstream
    ``V1KFReplica``): N pods sharing a role (worker/ps/master/...)."""

    replicas: Optional[int] = None
    restart_policy: Optional[str] = None
    environment: Optional[V1Environment] = None
    connections: Optional[list[str]] = None
    volumes: Optional[list[dict[str, Any]]] = None
    init: Optional[list[V1Init]] = None
    sidecars: Optional[list[V1Container]] = None
    container: Optional[V1Container] = None


class V1SchedulingPolicy(BaseSchema):
    min_available: Optional[int] = None
    queue: Optional[str] = None
    min_resources: Optional[dict[str, Any]] = None
    priority_class: Optional[str] = None
    schedule_timeout_seconds: Optional[int] = None


class _KFJob(_BaseRun):
    clean_pod_policy: Optional[str] = None
    scheduling_policy: Optional[V1SchedulingPolicy] = None
    # Training-runtime shortcut (same as V1TPUJob.runtime): replicas run the
    # built-in trainer as one SPMD program instead of a user container —
    # upstream's Kubeflow workloads (DDP/TF/Horovod) become mesh configs of
    # the owned runtime (SURVEY.md §7 stage 4)
    runtime: Optional[dict[str, Any]] = None
    # Declarative sharding overrides (docs/PARTITIONING.md): ordered
    # [regex, spec] pairs over /-joined param paths, overlaid on the
    # model's built-in partition rule set. Validated at compile time.
    partition_rules: Optional[list[Any]] = None


class V1TFJob(_KFJob):
    """TensorFlow multi-worker job (upstream delegates to Kubeflow ``TFJob``;
    our compiler maps it onto the TPU runtime — BASELINE config 3)."""

    kind: Literal["tfjob"] = "tfjob"
    enable_dynamic_worker: Optional[bool] = None
    success_policy: Optional[str] = None
    chief: Optional[V1KFReplica] = None
    ps: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    evaluator: Optional[V1KFReplica] = None


class V1PytorchJob(_KFJob):
    """PyTorch DDP job (upstream -> Kubeflow ``PyTorchJob`` + NCCL env;
    our compiler maps replicas -> mesh ``data`` axis — BASELINE config 2)."""

    kind: Literal["pytorchjob"] = "pytorchjob"
    master: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    nproc_per_node: Optional[int] = None
    elastic_policy: Optional[dict[str, Any]] = None


class V1MPIJob(_KFJob):
    """MPI/Horovod job (upstream -> ``mpirun`` + NCCL allreduce; ours ->
    the same user script with allreduce over ICI — BASELINE config 4)."""

    kind: Literal["mpijob"] = "mpijob"
    slots_per_worker: Optional[int] = None
    launcher: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None


class V1MXJob(_KFJob):
    kind: Literal["mxjob"] = "mxjob"
    mode: Optional[str] = None
    scheduler: Optional[V1KFReplica] = None
    server: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    tuner: Optional[V1KFReplica] = None
    tuner_tracker: Optional[V1KFReplica] = None
    tuner_server: Optional[V1KFReplica] = None


class V1XGBoostJob(_KFJob):
    kind: Literal["xgbjob"] = "xgbjob"
    master: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None


class V1PaddleJob(_KFJob):
    kind: Literal["paddlejob"] = "paddlejob"
    master: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None


class V1DaskJob(_BaseRun):
    kind: Literal["daskjob"] = "daskjob"
    job: Optional[V1KFReplica] = None
    worker: Optional[V1KFReplica] = None
    scheduler: Optional[V1KFReplica] = None


class V1RayReplica(V1KFReplica):
    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    group_name: Optional[str] = None
    ray_start_params: Optional[dict[str, str]] = None


class V1RayJob(_BaseRun):
    kind: Literal["rayjob"] = "rayjob"
    entrypoint: Optional[str] = None
    runtime_env: Optional[dict[str, Any]] = None
    metadata: Optional[dict[str, str]] = None
    ray_version: Optional[str] = None
    head: Optional[V1RayReplica] = None
    workers: Optional[list[V1RayReplica]] = None


class V1Parallelism(BaseSchema):
    """Mesh-axis sizes for the TPU runtime. Product must equal chip count.

    Axes follow the scaling-book decomposition: ``data`` (pure DP),
    ``fsdp`` (data + sharded params), ``model`` (tensor parallel),
    ``context`` (sequence/ring-attention parallel), ``expert`` (MoE),
    ``stage`` (pipeline). This replaces the reference's
    replicas+NCCL description of distribution (SURVEY.md §2 table).
    """

    data: int = 1
    fsdp: int = 1
    model: int = 1
    context: int = 1
    expert: int = 1
    stage: int = 1

    @property
    def total(self) -> int:
        return self.data * self.fsdp * self.model * self.context * self.expert * self.stage

    def axis_sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "stage": self.stage,
            "expert": self.expert,
            "context": self.context,
            "model": self.model,
        }


class V1TPUJob(_BaseRun):
    """TPU-native distributed job: one process per TPU-VM host of a slice.

    The operator provisions the slice via GKE nodeSelectors
    (``gke-tpu-accelerator``/``gke-tpu-topology``), creates one pod per host,
    and injects jax.distributed rendezvous env (coordinator address,
    num_processes, process_id) instead of NCCL ``MASTER_ADDR``/``WORLD_SIZE``
    (north star, BASELINE.json).
    """

    kind: Literal["tpujob"] = "tpujob"
    accelerator: str = "v5e"
    topology: Optional[str] = None  # e.g. "8x8"; or use `slices` alias e.g. v5e-64
    slice_alias: Optional[str] = None  # e.g. "v5e-64"
    num_slices: int = 1
    # Placement inside a parent slice (chip coordinates of this job's
    # sub-rectangle). Set by the tuner's sub-slice packing (BASELINE config
    # 5: 16 trials on one v5e-256); rendered as nodeSelector + PLX env.
    subslice_origin: Optional[list[int]] = None
    parallelism: Optional[V1Parallelism] = None
    init: Optional[list[V1Init]] = None
    sidecars: Optional[list[V1Container]] = None
    container: Optional[V1Container] = None
    # Training-runtime shortcut: run a built-in model instead of a container.
    # Partition-engine keys (docs/PARTITIONING.md): partition_rules
    # ([[regex, spec], ...] sharding overrides), import ({path, layout,
    # dtype} foreign-checkpoint ingest), lora ({rank, alpha, target}).
    runtime: Optional[dict[str, Any]] = None  # {model, config, precision, remat, ...}
    # Declarative sharding overrides, mergeable from the operation level
    # (the runtime dict's own partition_rules key wins). Compile-time
    # validated: bad regexes / no-match rules fail `polyaxon check`.
    partition_rules: Optional[list[Any]] = None

    @field_validator("accelerator")
    @classmethod
    def _check_accelerator(cls, v: str) -> str:
        v = v.lower()
        if v not in ACCELERATOR_SPECS:
            raise ValueError(f"Unknown accelerator '{v}'. Valid: {sorted(ACCELERATOR_SPECS)}")
        return v

    @model_validator(mode="after")
    def _check_slice(self) -> "V1TPUJob":
        # eager validation: a bad topology string must fail at parse time,
        # not when the scheduler first calls get_slice()
        if self.topology or self.slice_alias:
            self.get_slice()
        return self

    def get_slice(self) -> SliceTopology:
        if self.slice_alias:
            return SliceTopology.from_alias(self.slice_alias, self.num_slices)
        if not self.topology:
            raise ValueError("tpujob requires either 'topology' or 'sliceAlias'")
        return SliceTopology(
            accelerator=self.accelerator, topology=self.topology, num_slices=self.num_slices
        )


class V1JaxJob(V1TPUJob):
    """Alias kind for spec parity with the north star naming."""

    kind: Literal["jaxjob"] = "jaxjob"  # type: ignore[assignment]


class V1Tuner(BaseSchema):
    """The tuner component reference used by matrix pipelines
    (upstream ``V1Tuner``)."""

    hub_ref: Optional[str] = None
    presets: Optional[list[str]] = None
    queue: Optional[str] = None
    params: Optional[dict[str, V1Param]] = None
    container: Optional[V1Container] = None


class V1Notifier(_BaseRun):
    kind: Literal["notifier"] = "notifier"
    connections: Optional[list[str]] = None
    container: Optional[V1Container] = None


class V1CleanerJob(V1Job):
    kind: Literal["cleaner"] = "cleaner"  # type: ignore[assignment]


# V1Dag lives in dag.py (needs V1Operation; avoids a cycle via late import)

RunUnion = Annotated[
    Union[
        V1Job,
        V1Service,
        V1TFJob,
        V1PytorchJob,
        V1MPIJob,
        V1MXJob,
        V1XGBoostJob,
        V1PaddleJob,
        V1DaskJob,
        V1RayJob,
        V1TPUJob,
        V1JaxJob,
        V1Notifier,
        V1CleanerJob,
    ],
    Field(discriminator="kind"),
]
