"""Component/operation lifecycle knobs: environment, termination, plugins,
cache, hooks, build, schedules, events, dependencies.

Maps to upstream ``polyaxon._flow`` modules ``environment/termination/plugins/
cache/hooks/builds/schedules/events`` (SURVEY.md §2 "Polyflow schemas").
"""

from __future__ import annotations

from typing import Any, Optional, Union

from pydantic import Field

from .base import BaseSchema
from .io import V1Param
from .k8s import V1Affinity, V1HostAlias, V1PodDNSConfig, V1Toleration


class V1Environment(BaseSchema):
    """Pod-level runtime environment (upstream ``V1Environment``)."""

    labels: Optional[dict[str, str]] = None
    annotations: Optional[dict[str, str]] = None
    node_selector: Optional[dict[str, str]] = None
    affinity: Optional[V1Affinity] = None
    tolerations: Optional[list[V1Toleration]] = None
    node_name: Optional[str] = None
    service_account_name: Optional[str] = None
    host_aliases: Optional[list[V1HostAlias]] = None
    security_context: Optional[dict[str, Any]] = None
    image_pull_secrets: Optional[list[str]] = None
    host_network: Optional[bool] = None
    host_pid: Optional[bool] = None
    dns_policy: Optional[str] = None
    dns_config: Optional[V1PodDNSConfig] = None
    scheduler_name: Optional[str] = None
    priority_class_name: Optional[str] = None
    priority: Optional[int] = None
    restart_policy: Optional[str] = None


class V1Termination(BaseSchema):
    """Retry/TTL/timeout policy (upstream ``V1Termination``)."""

    max_retries: Optional[int] = None
    ttl: Optional[int] = None
    timeout: Optional[int] = None


class V1PluginsNotification(BaseSchema):
    connections: Optional[list[str]] = None
    trigger: Optional[str] = None


class V1Plugins(BaseSchema):
    """Toggles for the auxiliary machinery injected around the user container
    (upstream ``V1Plugins``): auth sidecar, log/artifact collection, etc."""

    auth: Optional[bool] = None
    docker: Optional[bool] = None
    shm: Optional[bool] = None
    mount_artifacts_store: Optional[bool] = None
    collect_artifacts: Optional[bool] = None
    collect_logs: Optional[bool] = None
    collect_resources: Optional[bool] = None
    sync_statuses: Optional[bool] = None
    auto_resume: Optional[bool] = None
    log_level: Optional[str] = None
    side_containers: Optional[bool] = None
    external_host: Optional[bool] = None
    sidecar: Optional[dict[str, Any]] = None
    notifications: Optional[list[V1PluginsNotification]] = None


class V1Cache(BaseSchema):
    """Run-result caching policy (upstream ``V1Cache``)."""

    disable: Optional[bool] = None
    ttl: Optional[int] = None
    io: Optional[list[str]] = None
    sections: Optional[list[str]] = None


class V1Hook(BaseSchema):
    """Post-run hook operation (upstream ``V1Hook``)."""

    connection: Optional[str] = None
    trigger: Optional[str] = None  # succeeded | failed | stopped | done
    hub_ref: Optional[str] = None
    conditions: Optional[str] = None
    presets: Optional[list[str]] = None
    params: Optional[dict[str, V1Param]] = None
    queue: Optional[str] = None
    disable_defaults: Optional[bool] = None


class V1Build(BaseSchema):
    """Pre-run image build step (upstream ``V1Build``)."""

    hub_ref: Optional[str] = None
    connection: Optional[str] = None
    queue: Optional[str] = None
    presets: Optional[list[str]] = None
    params: Optional[dict[str, V1Param]] = None
    run_patch: Optional[dict[str, Any]] = None
    patch_strategy: Optional[str] = None


class V1CronSchedule(BaseSchema):
    kind: str = Field(default="cron", frozen=True)
    cron: str
    start_at: Optional[str] = None
    end_at: Optional[str] = None
    max_runs: Optional[int] = None
    depends_on_past: Optional[bool] = None


class V1IntervalSchedule(BaseSchema):
    kind: str = Field(default="interval", frozen=True)
    frequency: Union[int, float, str]
    start_at: Optional[str] = None
    end_at: Optional[str] = None
    max_runs: Optional[int] = None
    depends_on_past: Optional[bool] = None


class V1DateTimeSchedule(BaseSchema):
    kind: str = Field(default="datetime", frozen=True)
    start_at: str


V1Schedule = Union[V1CronSchedule, V1IntervalSchedule, V1DateTimeSchedule]


class V1EventTrigger(BaseSchema):
    """Upstream-run event that triggers this op (upstream ``V1EventTrigger``)."""

    kinds: list[str]
    ref: str


class V1Cloning(BaseSchema):
    """How a run was cloned (upstream ``V1Cloning``); kinds: copy|restart|cache."""

    uuid: Optional[str] = None
    kind: Optional[str] = None
    artifacts: Optional[list[str]] = None


class TriggerPolicy:
    """Upstream ``V1TriggerPolicy`` values for DAG dependencies."""

    ALL_SUCCEEDED = "all_succeeded"
    ALL_FAILED = "all_failed"
    ALL_DONE = "all_done"
    ONE_SUCCEEDED = "one_succeeded"
    ONE_FAILED = "one_failed"
    ONE_DONE = "one_done"

    VALUES = {ALL_SUCCEEDED, ALL_FAILED, ALL_DONE, ONE_SUCCEEDED, ONE_FAILED, ONE_DONE}
