"""Polyflow-equivalent spec schemas (see SURVEY.md §2 "Polyflow schemas")."""

from .base import BaseSchema
from .component import V1Component
from .connections import (
    V1BucketConnection,
    V1ClaimConnection,
    V1AgentConfig,
    V1Connection,
    V1ConnectionKind,
    V1GitConnection,
    V1HostPathConnection,
    V1K8sResource,
)
from .dag import V1Dag
from .io import V1IO, V1Join, V1Param, V1Validation, validate_params_against_io
from .k8s import (
    V1Container,
    V1ContainerPort,
    V1EnvVar,
    V1ResourceRequirements,
    V1VolumeMount,
)
from .lifecycle import (
    V1Build,
    V1Cache,
    V1Cloning,
    V1CronSchedule,
    V1DateTimeSchedule,
    V1Environment,
    V1EventTrigger,
    V1Hook,
    V1IntervalSchedule,
    V1Plugins,
    V1Termination,
    TriggerPolicy,
)
from .matrix import (
    V1Bayes,
    V1FailureEarlyStopping,
    V1GridSearch,
    V1HpChoice,
    V1HpGeomSpace,
    V1HpLinSpace,
    V1HpLogNormal,
    V1HpLogSpace,
    V1HpLogUniform,
    V1HpNormal,
    V1HpPChoice,
    V1HpQLogNormal,
    V1HpQLogUniform,
    V1HpQNormal,
    V1HpQUniform,
    V1HpRange,
    V1HpUniform,
    V1Hyperband,
    V1Hyperopt,
    V1Iterative,
    V1Mapping,
    V1MetricEarlyStopping,
    V1OptimizationMetric,
    V1OptimizationResource,
    V1Pbt,
    V1RandomSearch,
)
from .operation import V1CompiledOperation, V1Operation
from .run import (
    V1CleanerJob,
    V1DaskJob,
    V1Init,
    V1JaxJob,
    V1Job,
    V1KFReplica,
    V1MPIJob,
    V1MXJob,
    V1Notifier,
    V1Parallelism,
    V1PaddleJob,
    V1PytorchJob,
    V1RayJob,
    V1RayReplica,
    V1RunKind,
    V1SchedulingPolicy,
    V1Service,
    V1TFJob,
    V1TPUJob,
    V1Tuner,
    V1XGBoostJob,
)
from .slo import GAUGE_OPS, SLO_KINDS, V1SLO, V1SLOPack
from .statuses import (
    DONE_STATUSES,
    RUNNABLE_STATUSES,
    V1StatusCondition,
    V1Statuses,
    can_transition,
    is_done,
)
from .tpu import (
    ACCELERATOR_SPECS,
    SliceTopology,
    SubSliceAssignment,
    default_topology,
    pack_subslices,
    parse_topology,
)
