"""Connections — declarations of external stores/resources mounted into runs
(upstream ``V1Connection`` + connection schemas; SURVEY.md §2 "FS /
connections")."""

from __future__ import annotations

from typing import Any, Literal, Optional, Union

from pydantic import Field

from .base import BaseSchema


class V1ConnectionKind:
    HOST_PATH = "host_path"
    VOLUME_CLAIM = "volume_claim"
    GCS = "gcs"
    S3 = "s3"
    WASB = "wasb"
    GIT = "git"
    REGISTRY = "registry"
    SSH = "ssh"
    SLACK = "slack"
    WEBHOOK = "webhook"
    CUSTOM = "custom"

    ARTIFACT_STORES = {HOST_PATH, VOLUME_CLAIM, GCS, S3, WASB}
    ALL = {HOST_PATH, VOLUME_CLAIM, GCS, S3, WASB, GIT, REGISTRY, SSH, SLACK, WEBHOOK, CUSTOM}


class V1BucketConnection(BaseSchema):
    bucket: str


class V1ClaimConnection(BaseSchema):
    volume_claim: str
    mount_path: str
    read_only: Optional[bool] = None


class V1HostPathConnection(BaseSchema):
    host_path: str
    mount_path: str
    read_only: Optional[bool] = None


class V1GitConnection(BaseSchema):
    url: str
    revision: Optional[str] = None
    flags: Optional[list[str]] = None


class V1K8sResource(BaseSchema):
    name: str
    items: Optional[list[str]] = None
    mount_path: Optional[str] = None
    is_requested: Optional[bool] = None


class V1Connection(BaseSchema):
    name: str
    kind: str
    description: Optional[str] = None
    tags: Optional[list[str]] = None
    schema_: Optional[
        Union[V1BucketConnection, V1ClaimConnection, V1HostPathConnection, V1GitConnection, dict[str, Any]]
    ] = Field(default=None, alias="schema")
    secret: Optional[V1K8sResource] = None
    config_map: Optional[V1K8sResource] = None
    env: Optional[list[dict[str, Any]]] = None
    annotations: Optional[dict[str, str]] = None

    def is_artifact_store(self) -> bool:
        return self.kind in V1ConnectionKind.ARTIFACT_STORES

    def store_path(self) -> str:
        """Root path/URI of the store this connection points at."""
        s = self.schema_
        if isinstance(s, V1BucketConnection):
            return s.bucket
        if isinstance(s, (V1ClaimConnection, V1HostPathConnection)):
            return s.mount_path
        if isinstance(s, dict):
            return s.get("bucket") or s.get("mountPath") or s.get("hostPath") or ""
        return ""


class V1AgentConfig(BaseSchema):
    """Agent-side deployment config (upstream's agent configuration file):
    the connections catalog runs may request, and which connection is the
    artifacts store. Loaded by `polyaxon server --agent-config <yaml>`."""

    connections: Optional[list[V1Connection]] = None
    artifacts_store: Optional[str] = None  # name of a connection above

    def connection_map(self) -> dict[str, V1Connection]:
        return {c.name: c for c in self.connections or []}

    def resolve_artifacts_store(self) -> Optional[V1Connection]:
        if not self.artifacts_store:
            return None
        conn = self.connection_map().get(self.artifacts_store)
        if conn is None:
            raise ValueError(
                f"artifacts_store {self.artifacts_store!r} names no declared "
                f"connection"
            )
        if not conn.is_artifact_store():
            raise ValueError(
                f"connection {conn.name!r} (kind {conn.kind}) cannot serve "
                f"as an artifacts store"
            )
        return conn
