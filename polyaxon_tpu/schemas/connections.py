"""Connections — declarations of external stores/resources mounted into runs
(upstream ``V1Connection`` + connection schemas; SURVEY.md §2 "FS /
connections")."""

from __future__ import annotations

from typing import Any, Literal, Optional, Union

from pydantic import Field

from .base import BaseSchema


class V1ConnectionKind:
    HOST_PATH = "host_path"
    VOLUME_CLAIM = "volume_claim"
    GCS = "gcs"
    S3 = "s3"
    WASB = "wasb"
    GIT = "git"
    REGISTRY = "registry"
    SSH = "ssh"
    SLACK = "slack"
    WEBHOOK = "webhook"
    CUSTOM = "custom"

    ARTIFACT_STORES = {HOST_PATH, VOLUME_CLAIM, GCS, S3, WASB}
    ALL = {HOST_PATH, VOLUME_CLAIM, GCS, S3, WASB, GIT, REGISTRY, SSH, SLACK, WEBHOOK, CUSTOM}


class V1BucketConnection(BaseSchema):
    bucket: str


class V1ClaimConnection(BaseSchema):
    volume_claim: str
    mount_path: str
    read_only: Optional[bool] = None


class V1HostPathConnection(BaseSchema):
    host_path: str
    mount_path: str
    read_only: Optional[bool] = None


class V1GitConnection(BaseSchema):
    url: str
    revision: Optional[str] = None
    flags: Optional[list[str]] = None


class V1K8sResource(BaseSchema):
    name: str
    items: Optional[list[str]] = None
    mount_path: Optional[str] = None
    is_requested: Optional[bool] = None


class V1Connection(BaseSchema):
    name: str
    kind: str
    description: Optional[str] = None
    tags: Optional[list[str]] = None
    schema_: Optional[
        Union[V1BucketConnection, V1ClaimConnection, V1HostPathConnection, V1GitConnection, dict[str, Any]]
    ] = Field(default=None, alias="schema")
    secret: Optional[V1K8sResource] = None
    config_map: Optional[V1K8sResource] = None
    env: Optional[list[dict[str, Any]]] = None
    annotations: Optional[dict[str, str]] = None

    def is_artifact_store(self) -> bool:
        return self.kind in V1ConnectionKind.ARTIFACT_STORES

    def store_path(self) -> str:
        """Root path/URI of the store this connection points at."""
        s = self.schema_
        if isinstance(s, V1BucketConnection):
            return s.bucket
        if isinstance(s, (V1ClaimConnection, V1HostPathConnection)):
            return s.mount_path
        if isinstance(s, dict):
            return s.get("bucket") or s.get("mountPath") or s.get("hostPath") or ""
        return ""
