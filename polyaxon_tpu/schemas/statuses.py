"""Run status lifecycle (upstream ``polyaxon.lifecycle`` ``V1Statuses``;
SURVEY.md §2 "API service" row)."""

from __future__ import annotations

import datetime
from enum import Enum
from typing import Optional

from .base import BaseSchema


class V1Statuses(str, Enum):
    CREATED = "created"
    RESUMING = "resuming"
    ON_SCHEDULE = "on_schedule"
    COMPILED = "compiled"
    QUEUED = "queued"
    SCHEDULED = "scheduled"
    STARTING = "starting"
    RUNNING = "running"
    PROCESSING = "processing"
    STOPPING = "stopping"
    STOPPED = "stopped"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    UPSTREAM_FAILED = "upstream_failed"
    RETRYING = "retrying"
    UNSCHEDULABLE = "unschedulable"
    WARNING = "warning"
    UNKNOWN = "unknown"
    DONE = "done"
    SKIPPED = "skipped"


DONE_STATUSES = {
    V1Statuses.SUCCEEDED,
    V1Statuses.FAILED,
    V1Statuses.STOPPED,
    V1Statuses.UPSTREAM_FAILED,
    V1Statuses.SKIPPED,
    V1Statuses.DONE,
}

RUNNABLE_STATUSES = {
    V1Statuses.CREATED,
    V1Statuses.RESUMING,
    V1Statuses.COMPILED,
    V1Statuses.QUEUED,
    V1Statuses.RETRYING,
}

# Legal forward transitions; anything -> stopping/stopped is always allowed.
_TRANSITIONS: dict[V1Statuses, set[V1Statuses]] = {
    V1Statuses.CREATED: {V1Statuses.COMPILED, V1Statuses.ON_SCHEDULE, V1Statuses.RESUMING, V1Statuses.SKIPPED},
    V1Statuses.RESUMING: {V1Statuses.COMPILED},
    V1Statuses.ON_SCHEDULE: {V1Statuses.COMPILED},
    V1Statuses.COMPILED: {V1Statuses.QUEUED},
    V1Statuses.QUEUED: {V1Statuses.SCHEDULED, V1Statuses.UNSCHEDULABLE},
    V1Statuses.UNSCHEDULABLE: {V1Statuses.QUEUED, V1Statuses.SCHEDULED, V1Statuses.FAILED},
    V1Statuses.SCHEDULED: {V1Statuses.STARTING, V1Statuses.RUNNING, V1Statuses.FAILED},
    V1Statuses.STARTING: {V1Statuses.RUNNING, V1Statuses.FAILED, V1Statuses.RETRYING},
    V1Statuses.RUNNING: {
        V1Statuses.PROCESSING,
        V1Statuses.SUCCEEDED,
        V1Statuses.FAILED,
        V1Statuses.WARNING,
        V1Statuses.RETRYING,
    },
    V1Statuses.PROCESSING: {V1Statuses.SUCCEEDED, V1Statuses.FAILED, V1Statuses.RUNNING},
    V1Statuses.WARNING: {V1Statuses.RUNNING, V1Statuses.SUCCEEDED, V1Statuses.FAILED},
    V1Statuses.RETRYING: {V1Statuses.COMPILED, V1Statuses.QUEUED, V1Statuses.FAILED},
}


def can_transition(src: V1Statuses, dst: V1Statuses) -> bool:
    if src == dst:
        return False
    if src in DONE_STATUSES:
        return False
    # terminal interventions are legal from any non-done state: stop requests,
    # lost-contact, and failures (e.g. compile errors fail a `created` run)
    if dst in (V1Statuses.STOPPING, V1Statuses.STOPPED, V1Statuses.UNKNOWN, V1Statuses.FAILED):
        return True
    return dst in _TRANSITIONS.get(src, set())


def is_done(status: V1Statuses | str) -> bool:
    return V1Statuses(status) in DONE_STATUSES


class V1StatusCondition(BaseSchema):
    """One entry in a run's status history (upstream ``V1StatusCondition``)."""

    type: V1Statuses
    status: bool = True
    reason: Optional[str] = None
    message: Optional[str] = None
    last_update_time: Optional[str] = None
    last_transition_time: Optional[str] = None

    @classmethod
    def get_condition(
        cls,
        type: V1Statuses,
        reason: Optional[str] = None,
        message: Optional[str] = None,
    ) -> "V1StatusCondition":
        now = datetime.datetime.now(datetime.timezone.utc).isoformat()
        return cls(
            type=type,
            status=True,
            reason=reason,
            message=message,
            last_update_time=now,
            last_transition_time=now,
        )
