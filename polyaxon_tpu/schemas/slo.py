"""SLO spec schemas (ISSUE 20): declarative objectives evaluated by
``obs.slo`` against the metrics-history recorder.

The spec follows the multi-window multi-burn-rate alerting shape (the
SRE-workbook recipe): an alert fires only when BOTH a fast window (is it
bad right now?) and a slow window (has it been bad long enough to spend
real budget?) burn error budget faster than their thresholds. Four
spec kinds cover the families the repo actually exports:

- ``latency`` — fraction of histogram observations over ``threshold_s``
  is the error rate (good = observations at or under the threshold).
- ``ratio``   — ``bad_family`` / ``total_family`` counter increase ratio
  (e.g. rejected / requests for serving availability).
- ``events``  — ``family`` counter increase per hour vs
  ``budget_per_hour`` (e.g. training NaN anomalies).
- ``gauge``   — fraction of recorded buckets where the gauge breaches
  ``threshold`` under ``op`` (e.g. ``polyaxon_store_degraded >= 1``).

Burn rate is always ``error_rate / (1 - objective)`` (events use
``rate / budget``), so a threshold like ``fast_burn: 14`` reads the
standard way: the budget is being consumed 14x faster than break-even.
"""

from __future__ import annotations

from typing import List, Optional

from pydantic import field_validator, model_validator

from .base import BaseSchema

SLO_KINDS = ("latency", "ratio", "events", "gauge")
GAUGE_OPS = (">=", ">", "<=", "<")


class V1SLO(BaseSchema):
    """One service-level objective plus its burn-rate alert policy."""

    name: str
    kind: str = "ratio"
    description: Optional[str] = None
    severity: str = "page"

    # target: e.g. 0.999 = 99.9% of events good / budget fraction 0.001
    objective: float = 0.999

    # kind-specific selectors
    family: Optional[str] = None        # latency / events / gauge
    bad_family: Optional[str] = None    # ratio numerator
    total_family: Optional[str] = None  # ratio denominator
    threshold_s: Optional[float] = None  # latency: good <= threshold_s
    threshold: Optional[float] = None    # gauge comparison value
    op: str = ">="                       # gauge comparison operator
    budget_per_hour: Optional[float] = None  # events: allowed rate

    # multi-window burn-rate policy
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn: float = 14.0
    slow_burn: float = 6.0

    # alert state machine knobs
    for_s: float = 0.0                   # dwell before pending -> firing
    renotify_interval_s: float = 3600.0  # re-notify while still firing

    @field_validator("kind")
    @classmethod
    def _kind_known(cls, v: str) -> str:
        if v not in SLO_KINDS:
            raise ValueError(f"kind must be one of {SLO_KINDS}, got {v!r}")
        return v

    @field_validator("op")
    @classmethod
    def _op_known(cls, v: str) -> str:
        if v not in GAUGE_OPS:
            raise ValueError(f"op must be one of {GAUGE_OPS}, got {v!r}")
        return v

    @field_validator("objective")
    @classmethod
    def _objective_sane(cls, v: float) -> float:
        if not (0.0 < v < 1.0):
            raise ValueError("objective must be in (0, 1)")
        return v

    @model_validator(mode="after")
    def _kind_fields(self) -> "V1SLO":
        if self.kind == "latency":
            if not self.family or self.threshold_s is None:
                raise ValueError(
                    "latency SLO needs family + threshold_s")
        elif self.kind == "ratio":
            if not self.bad_family or not self.total_family:
                raise ValueError(
                    "ratio SLO needs bad_family + total_family")
        elif self.kind == "events":
            if not self.family or not self.budget_per_hour:
                raise ValueError(
                    "events SLO needs family + budget_per_hour")
        elif self.kind == "gauge":
            if not self.family or self.threshold is None:
                raise ValueError("gauge SLO needs family + threshold")
        if self.slow_window_s < self.fast_window_s:
            raise ValueError("slow_window_s must be >= fast_window_s")
        return self

    def families(self) -> List[str]:
        """Every metric family this spec reads — the drift surface
        analyzer R8 checks against the EXPECTED_FAMILIES contract."""
        out = [f for f in (self.family, self.bad_family,
                           self.total_family) if f]
        return out


class V1SLOPack(BaseSchema):
    """A YAML-loadable bundle of SLOs (``polyaxon slo`` / agent config)."""

    slos: List[V1SLO] = []

    @model_validator(mode="after")
    def _unique_names(self) -> "V1SLOPack":
        names = [s.name for s in self.slos]
        if len(names) != len(set(names)):
            raise ValueError("duplicate SLO names in pack")
        return self
