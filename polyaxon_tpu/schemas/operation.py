"""``V1Operation`` — a component + bindings, ready to run; and
``V1CompiledOperation`` — the compiler's fully-resolved output
(upstream ``V1Operation``/``V1CompiledOperation``, SURVEY.md §3a)."""

from __future__ import annotations

from typing import Any, Optional

from pydantic import field_validator, model_validator

from .base import BaseSchema, _deep_merge
from .component import V1Component
from .io import V1IO, V1Join, V1Param
from .lifecycle import (
    V1Build,
    V1Cache,
    V1CronSchedule,
    V1DateTimeSchedule,
    V1EventTrigger,
    V1Hook,
    V1IntervalSchedule,
    V1Plugins,
    V1Termination,
    TriggerPolicy,
)
from .matrix import MatrixUnion


class V1Placement(BaseSchema):
    """Cross-cluster placement constraints (ISSUE 16,
    docs/SCHEDULING.md "Placement and spillover"): ``cluster`` HARD-pins
    the run to one named cluster backend (it parks rather than spill if
    that cluster is full, and parks with ``ClusterLost`` if it dies);
    ``chipType`` restricts scheduling and spillover to clusters of one
    TPU generation. The chip family is validated here (schema level);
    cluster names are validated at compile time against the live
    registry, with nearest-cluster hints."""

    cluster: Optional[str] = None
    chip_type: Optional[str] = None

    @field_validator("chip_type")
    @classmethod
    def _check_chip_type(cls, v: Optional[str]) -> Optional[str]:
        from .tpu import ACCELERATOR_SPECS

        if v is not None and v.partition("-")[0] not in ACCELERATOR_SPECS:
            raise ValueError(
                f"Unknown chip family '{v}' (one of: "
                f"{', '.join(sorted(ACCELERATOR_SPECS))})")
        return v


class _OpCommon(BaseSchema):
    version: Optional[float] = None
    kind: Optional[str] = None  # "operation"
    name: Optional[str] = None
    description: Optional[str] = None
    tags: Optional[list[str]] = None
    presets: Optional[list[str]] = None
    queue: Optional[str] = None
    # scheduling priority class (ISSUE 15, docs/SCHEDULING.md): "high"
    # may preempt running lower-class training work, "preemptible" is
    # first in line to be preempted; absent = "normal". Compile-time
    # validated — a typo fails the polyaxonfile check, not the scheduler.
    priority: Optional[str] = None
    # cross-cluster placement constraints (ISSUE 16): hard cluster pin
    # and/or chip-family restriction, compile-time validated against the
    # cluster registry (nearest-cluster hints on a typo)
    placement: Optional[V1Placement] = None
    cache: Optional[V1Cache] = None
    termination: Optional[V1Termination] = None
    plugins: Optional[V1Plugins] = None
    build: Optional[V1Build] = None
    hooks: Optional[list[V1Hook]] = None
    params: Optional[dict[str, V1Param]] = None
    matrix: Optional[MatrixUnion] = None
    joins: Optional[list[V1Join]] = None
    schedule: Optional[Any] = None
    events: Optional[list[V1EventTrigger]] = None
    dependencies: Optional[list[str]] = None
    trigger: Optional[str] = None
    conditions: Optional[str] = None
    skip_on_upstream_skip: Optional[bool] = None
    run_patch: Optional[dict[str, Any]] = None
    patch_strategy: Optional[str] = None
    is_preset: Optional[bool] = None
    is_approved: Optional[bool] = None
    cost: Optional[float] = None

    @field_validator("trigger")
    @classmethod
    def _check_trigger(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and v not in TriggerPolicy.VALUES:
            raise ValueError(f"Unknown trigger policy '{v}'")
        return v

    @field_validator("priority")
    @classmethod
    def _check_priority(cls, v: Optional[str]) -> Optional[str]:
        from ..tenancy import PRIORITY_CLASSES

        if v is not None and v not in PRIORITY_CLASSES:
            raise ValueError(
                f"Unknown priority class '{v}' (one of: "
                f"{', '.join(sorted(PRIORITY_CLASSES))})")
        return v

    @field_validator("schedule", mode="before")
    @classmethod
    def _parse_schedule(cls, v: Any) -> Any:
        if v is None or not isinstance(v, dict):
            return v
        kinds = {
            "cron": V1CronSchedule,
            "interval": V1IntervalSchedule,
            "datetime": V1DateTimeSchedule,
        }
        k = v.get("kind")
        if k not in kinds:
            raise ValueError(f"Unknown schedule kind '{k}'")
        return kinds[k].from_dict(v)


class V1Operation(_OpCommon):
    # Exactly one of these identifies the component to run:
    component: Optional[V1Component] = None  # inline (YAML `component:` or `hubRef`-free file)
    hub_ref: Optional[str] = None
    path_ref: Optional[str] = None
    url_ref: Optional[str] = None
    dag_ref: Optional[str] = None
    template: Optional[dict[str, Any]] = None

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and v != "operation":
            raise ValueError(f"Operation kind must be 'operation', got '{v}'")
        return v

    @model_validator(mode="after")
    def _one_ref(self) -> "V1Operation":
        refs = [
            r
            for r in (self.component, self.hub_ref, self.path_ref, self.url_ref, self.dag_ref)
            if r is not None
        ]
        if len(refs) > 1:
            raise ValueError(
                "Operation must set exactly one of: component, hubRef, pathRef, urlRef, dagRef"
            )
        if not refs and not self.is_preset and self.template is None:
            raise ValueError(
                "Operation must reference a component (one of: component, hubRef, "
                "pathRef, urlRef, dagRef) unless it is a preset or template"
            )
        return self

    def has_component(self) -> bool:
        return self.component is not None


class V1CompiledOperation(_OpCommon):
    """The fully-resolved operation the scheduler executes: component inlined,
    presets merged, params validated & defaulted, run patched."""

    inputs: Optional[list[V1IO]] = None
    outputs: Optional[list[V1IO]] = None
    run: Optional[Any] = None

    @field_validator("run", mode="before")
    @classmethod
    def _validate_run(cls, v: Any) -> Any:
        return V1Component._validate_run(v)

    @classmethod
    def from_operation(cls, op: V1Operation, component: Optional[V1Component] = None) -> "V1CompiledOperation":
        """Inline the component into the op; op-level fields win (upstream
        compiler ``resolve()`` step 1, SURVEY.md §3a)."""
        comp = component or op.component
        if comp is None:
            raise ValueError("Operation has no inline component and none was provided")
        comp.validate()
        run_d = comp.run.to_dict() if comp.run is not None else None
        if op.run_patch:
            strategy = op.patch_strategy or "post_merge"
            if strategy == "replace":
                run_d = dict(op.run_patch)
            elif strategy == "isnull":
                run_d = run_d or dict(op.run_patch)
            elif strategy == "post_merge":
                run_d = _deep_merge(run_d or {}, op.run_patch)
            else:  # pre_merge
                run_d = _deep_merge(dict(op.run_patch), run_d or {})
        op_d = op.to_dict()
        comp_d = comp.to_dict()

        def pick(*fields: str) -> dict[str, Any]:
            """op-level value wins; fall back to the component's."""
            out = {}
            for f in fields:
                v = op_d.get(f)
                if v is None:
                    v = comp_d.get(f)
                if v is not None:
                    out[f] = v
            return out

        data: dict[str, Any] = {
            "kind": "compiled_operation",
            "tags": sorted(set(op.tags or []) | set(comp.tags or [])) or None,
            **pick(
                "version", "name", "description", "presets", "queue", "cache",
                "priority", "placement", "termination", "plugins", "build",
                "hooks", "isApproved", "cost",
            ),
            # op-only sections pass through verbatim
            **{
                k: op_d.get(k)
                for k in (
                    "params", "matrix", "joins", "schedule", "events", "dependencies",
                    "trigger", "conditions", "skipOnUpstreamSkip",
                )
            },
            "inputs": comp_d.get("inputs"),
            "outputs": comp_d.get("outputs"),
            "run": run_d,
        }
        return cls.from_dict({k: v for k, v in data.items() if v is not None})

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and v != "compiled_operation":
            raise ValueError(f"CompiledOperation kind must be 'compiled_operation', got '{v}'")
        return v

    def get_run_kind(self) -> Optional[str]:
        return getattr(self.run, "kind", None) if self.run is not None else None
