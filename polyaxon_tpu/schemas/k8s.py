"""Minimal Kubernetes-compatible container/pod primitives.

Upstream polyaxon embeds full ``kubernetes.client`` swagger models in specs
(SURVEY.md §2 "Compiler"); we define the small subset the framework actually
renders, wire-compatible with K8s YAML (camelCase), so polyaxonfiles written
for upstream parse unchanged.
"""

from __future__ import annotations

from typing import Any, Optional

from pydantic import Field

from .base import BaseSchema


class V1EnvVar(BaseSchema):
    name: str
    value: Optional[str] = None
    value_from: Optional[dict[str, Any]] = None


class V1ResourceRequirements(BaseSchema):
    limits: Optional[dict[str, Any]] = None
    requests: Optional[dict[str, Any]] = None


class V1VolumeMount(BaseSchema):
    name: str
    mount_path: Optional[str] = None
    sub_path: Optional[str] = None
    read_only: Optional[bool] = None


class V1ContainerPort(BaseSchema):
    container_port: int
    name: Optional[str] = None
    host_port: Optional[int] = None
    protocol: Optional[str] = None


class V1Container(BaseSchema):
    """A container spec (subset of k8s core/v1 Container)."""

    name: Optional[str] = None
    image: Optional[str] = None
    image_pull_policy: Optional[str] = None
    command: Optional[list[str]] = None
    args: Optional[list[str]] = None
    env: Optional[list[V1EnvVar]] = None
    env_from: Optional[list[dict[str, Any]]] = None
    resources: Optional[V1ResourceRequirements] = None
    volume_mounts: Optional[list[V1VolumeMount]] = None
    working_dir: Optional[str] = None
    ports: Optional[list[V1ContainerPort]] = None
    stdin: Optional[bool] = None
    tty: Optional[bool] = None

    def get_env_dict(self) -> dict[str, str]:
        return {e.name: e.value or "" for e in self.env or []}


class V1Affinity(BaseSchema):
    model_config = BaseSchema.model_config | {"extra": "allow"}


class V1Toleration(BaseSchema):
    key: Optional[str] = None
    operator: Optional[str] = None
    value: Optional[str] = None
    effect: Optional[str] = None
    toleration_seconds: Optional[int] = None


class V1HostAlias(BaseSchema):
    ip: Optional[str] = None
    hostnames: Optional[list[str]] = None


class V1PodDNSConfig(BaseSchema):
    nameservers: Optional[list[str]] = None
    searches: Optional[list[str]] = None
    options: Optional[list[dict[str, Any]]] = None
