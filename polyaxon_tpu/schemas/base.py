"""Base machinery for all spec schemas.

Equivalent in role to upstream polyaxon's ``polyaxon._schemas.base``
(reference mount empty — see SURVEY.md §2 "Polyflow schemas"): every spec
object is a camelCase-serialized, strictly-validated model with
``from_dict``/``to_dict``/``from_yaml`` round-tripping.
"""

from __future__ import annotations

from typing import Any, Type, TypeVar

import yaml
from pydantic import BaseModel, ConfigDict

T = TypeVar("T", bound="BaseSchema")


def to_camel(s: str) -> str:
    parts = s.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


class BaseSchema(BaseModel):
    """Base for all polyflow-style spec objects.

    - snake_case python attrs <-> camelCase wire format (polyaxonfile YAML).
    - unknown fields rejected (spec typo protection, matching upstream's
      strict marshmallow/pydantic validation behavior).
    """

    model_config = ConfigDict(
        populate_by_name=True,
        alias_generator=to_camel,
        extra="forbid",
        validate_assignment=True,
        protected_namespaces=(),
    )

    @classmethod
    def from_dict(cls: Type[T], data: dict[str, Any]) -> T:
        return cls.model_validate(data)

    @classmethod
    def from_yaml(cls: Type[T], text: str) -> T:
        data = yaml.safe_load(text)
        if not isinstance(data, dict):
            raise ValueError(f"Expected a mapping for {cls.__name__}, got {type(data).__name__}")
        return cls.from_dict(data)

    def to_dict(self, exclude_none: bool = True) -> dict[str, Any]:
        return self.model_dump(by_alias=True, exclude_none=exclude_none, mode="json")

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    def clone(self: T) -> T:
        return self.model_copy(deep=True)

    def patch(self: T, other: T | dict[str, Any], strategy: str = "post_merge") -> T:
        """Merge ``other`` into self following polyaxon patch strategies.

        Strategies (upstream ``V1PatchStrategy``): ``replace``, ``isnull``
        (only fill missing), ``post_merge`` (other wins), ``pre_merge``
        (self wins on conflicts, recursing into dicts).
        """
        if isinstance(other, BaseSchema):
            other_d = other.to_dict()
        else:
            other_d = dict(other)
        mine = self.to_dict()
        if strategy == "replace":
            merged = other_d
        elif strategy == "isnull":
            # only fill fields entirely missing on self (shallow, per upstream)
            merged = dict(mine)
            for k, v in other_d.items():
                if k not in merged or merged[k] is None:
                    merged[k] = v
        elif strategy == "post_merge":
            merged = _deep_merge(mine, other_d)
        elif strategy == "pre_merge":
            merged = _deep_merge(other_d, mine)
        else:
            raise ValueError(f"Unknown patch strategy: {strategy}")
        return type(self).from_dict(merged)


def _deep_merge(base: dict, override: dict) -> dict:
    """Recursive dict merge; ``override`` wins on leaf conflicts."""
    out = dict(base)
    for k, v in override.items():
        if v is None:
            continue
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
