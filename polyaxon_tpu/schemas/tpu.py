"""TPU slice topology math: accelerator generations, slice shapes, host
counts, and ICI sub-slice splitting for trial packing.

This replaces the reference's GPU scheduling surface (``nvidia.com/gpu``
resources + NCCL env; SURVEY.md §2 "absent components" table) with
first-class TPU topology objects. The hypertune scheduler uses
``SliceTopology.subdivide`` to pack parallel trials onto ICI sub-slices
(BASELINE config 5: 16 ViT trials on one v5e-256).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import reduce
from typing import Optional

from pydantic import field_validator, model_validator

from .base import BaseSchema

# Per-generation facts: chips per TPU-VM host, topology rank (2D for v5e/v6e,
# 3D for v4/v5p), max chips in a single-host slice, HBM GiB and peak bf16
# TFLOP/s per chip (public figures; used by the MFU meter and scheduler).
ACCELERATOR_SPECS: dict[str, dict] = {
    "v4": {"chips_per_host": 4, "dims": 3, "hbm_gib": 32, "bf16_tflops": 275.0},
    "v5e": {"chips_per_host": 4, "dims": 2, "hbm_gib": 16, "bf16_tflops": 197.0},
    "v5p": {"chips_per_host": 4, "dims": 3, "hbm_gib": 95, "bf16_tflops": 459.0},
    "v6e": {"chips_per_host": 4, "dims": 2, "hbm_gib": 32, "bf16_tflops": 918.0},
}

# GKE accelerator type strings (nodeSelector cloud.google.com/gke-tpu-accelerator)
GKE_ACCELERATOR = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}


def parse_topology(topology: str) -> tuple[int, ...]:
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError:
        raise ValueError(f"Bad topology string {topology!r}; expected e.g. '4x4' or '4x4x8'")
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"Bad topology string {topology!r}")
    return dims


class SliceTopology(BaseSchema):
    """A concrete TPU slice: generation + ICI mesh shape.

    ``v5e-64`` == SliceTopology(accelerator='v5e', topology='8x8').
    """

    accelerator: str
    topology: str
    num_slices: int = 1  # >1 = multislice over DCN (megascale)

    @field_validator("accelerator")
    @classmethod
    def _check_acc(cls, v: str) -> str:
        v = v.lower()
        if v not in ACCELERATOR_SPECS:
            raise ValueError(f"Unknown accelerator '{v}'. Valid: {sorted(ACCELERATOR_SPECS)}")
        return v

    @model_validator(mode="after")
    def _check_topology(self) -> "SliceTopology":
        dims = parse_topology(self.topology)
        want = ACCELERATOR_SPECS[self.accelerator]["dims"]
        if len(dims) not in (1, want):
            raise ValueError(
                f"{self.accelerator} topologies are {want}D; got '{self.topology}'"
            )
        return self

    # -- derived quantities --------------------------------------------------

    @property
    def dims(self) -> tuple[int, ...]:
        return parse_topology(self.topology)

    @property
    def chips_per_slice(self) -> int:
        return reduce(lambda a, b: a * b, self.dims, 1)

    @property
    def num_chips(self) -> int:
        return self.chips_per_slice * self.num_slices

    @property
    def chips_per_host(self) -> int:
        spec = ACCELERATOR_SPECS[self.accelerator]
        # single-host slices own all their chips (e.g. v5e 2x4 = 8 chips, 1 host)
        if self.chips_per_slice <= 8 and spec["dims"] == 2:
            return self.chips_per_slice
        return spec["chips_per_host"]

    @property
    def hosts_per_slice(self) -> int:
        return max(1, math.ceil(self.chips_per_slice / self.chips_per_host))

    @property
    def num_hosts(self) -> int:
        return self.hosts_per_slice * self.num_slices

    @property
    def bf16_tflops_per_chip(self) -> float:
        return ACCELERATOR_SPECS[self.accelerator]["bf16_tflops"]

    @property
    def hbm_gib_per_chip(self) -> float:
        return ACCELERATOR_SPECS[self.accelerator]["hbm_gib"]

    @property
    def gke_accelerator(self) -> str:
        return GKE_ACCELERATOR[self.accelerator]

    @property
    def gke_topology(self) -> str:
        return self.topology

    @classmethod
    def from_alias(cls, alias: str, num_slices: int = 1) -> "SliceTopology":
        """Parse shorthand like 'v5e-64' / 'v5p-128' into a default topology."""
        gen, _, chips_s = alias.partition("-")
        gen = gen.lower()
        if gen not in ACCELERATOR_SPECS:
            raise ValueError(f"Unknown accelerator alias '{alias}'")
        chips = int(chips_s)
        return cls(accelerator=gen, topology=default_topology(gen, chips), num_slices=num_slices)

    def node_selectors(self) -> dict[str, str]:
        """GKE nodeSelector labels that place pods on this slice shape."""
        return {
            "cloud.google.com/gke-tpu-accelerator": self.gke_accelerator,
            "cloud.google.com/gke-tpu-topology": self.gke_topology,
        }

    def tpu_resources(self) -> dict[str, int]:
        """Per-pod ``google.com/tpu`` resource request (chips on one host)."""
        return {"google.com/tpu": self.chips_per_host}

    def subdivide(self, sub: "SliceTopology") -> int:
        """How many ``sub`` slices tile into this slice (ICI-contiguous).

        TPU slices split only along axis-aligned rectangles whose dims divide
        the parent dims — this is the constraint behind topology-aware trial
        packing (SURVEY.md §7 hard part (a)).
        """
        if sub.accelerator != self.accelerator:
            return 0
        a, b = self.dims, sub.dims
        if len(a) != len(b):
            return 0
        if any(x % y != 0 for x, y in zip(a, b)):
            return 0
        return reduce(lambda p, q: p * q, (x // y for x, y in zip(a, b)), 1)


def default_topology(accelerator: str, num_chips: int) -> str:
    """Pick the standard GKE topology for a chip count (e.g. v5e 64 -> 8x8)."""
    spec = ACCELERATOR_SPECS[accelerator]
    if spec["dims"] == 2:
        std = {
            1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8",
            64: "8x8", 128: "8x16", 256: "16x16",
        }
        if num_chips in std:
            return std[num_chips]
        side = int(math.isqrt(num_chips))
        if side * side == num_chips:
            return f"{side}x{side}"
        raise ValueError(f"No standard {accelerator} topology for {num_chips} chips")
    # 3D generations: standard shapes are 4-multiples per dim
    std3 = {
        8: "2x2x1", 16: "2x2x4", 32: "2x4x4", 64: "4x4x4", 128: "4x4x8",
        256: "4x8x8", 512: "8x8x8", 1024: "8x8x16", 2048: "8x16x16",
    }
    if num_chips in std3:
        return std3[num_chips]
    raise ValueError(f"No standard {accelerator} topology for {num_chips} chips")


@dataclass(frozen=True)
class SubSliceAssignment:
    """A trial's placement inside a parent slice: which rectangle of chips."""

    index: int
    origin: tuple[int, ...]
    shape: tuple[int, ...]

    @property
    def chips(self) -> int:
        return reduce(lambda a, b: a * b, self.shape, 1)


def pack_subslices(parent: SliceTopology, sub: SliceTopology, n: int) -> list[SubSliceAssignment]:
    """Assign up to ``n`` axis-aligned sub-rectangles of ``sub``'s shape inside
    ``parent``. Raises if they don't fit. Deterministic row-major order."""
    capacity = parent.subdivide(sub)
    if capacity == 0:
        raise ValueError(
            f"Sub-slice {sub.topology} does not tile parent {parent.topology} "
            f"({parent.accelerator})"
        )
    if n > capacity:
        raise ValueError(f"Requested {n} sub-slices but only {capacity} fit")
    pdims, sdims = parent.dims, sub.dims
    counts = [p // s for p, s in zip(pdims, sdims)]
    out: list[SubSliceAssignment] = []
    # row-major enumeration over the grid of sub-slice positions
    total = reduce(lambda a, b: a * b, counts, 1)
    for idx in range(min(n, total)):
        rem, coord = idx, []
        for c in reversed(counts):
            coord.append(rem % c)
            rem //= c
        coord = tuple(reversed(coord))
        origin = tuple(c * s for c, s in zip(coord, sdims))
        out.append(SubSliceAssignment(index=idx, origin=origin, shape=tuple(sdims)))
    return out
