"""``V1Dag`` — pipeline runtime: a graph of operations (upstream ``V1Dag``,
SURVEY.md §2 "Runtime kinds" / §3c pipelines)."""

from __future__ import annotations

from typing import Any, Literal, Optional

from pydantic import field_validator

from .base import BaseSchema
from .component import V1Component
from .lifecycle import V1Environment
from .matrix import EarlyStoppingUnion
from .operation import V1Operation


class V1Dag(BaseSchema):
    kind: Literal["dag"] = "dag"
    operations: Optional[list[V1Operation]] = None
    components: Optional[list[V1Component]] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStoppingUnion]] = None
    environment: Optional[V1Environment] = None
    connections: Optional[list[str]] = None
    volumes: Optional[list[dict[str, Any]]] = None

    @field_validator("operations")
    @classmethod
    def _names(cls, ops: Optional[list[V1Operation]]) -> Optional[list[V1Operation]]:
        if ops:
            names = [o.name for o in ops if o.name]
            dupes = {n for n in names if names.count(n) > 1}
            if dupes:
                raise ValueError(f"Duplicate operation names in dag: {sorted(dupes)}")
        return ops

    def get_component(self, name: str) -> Optional[V1Component]:
        for c in self.components or []:
            if c.name == name:
                return c
        return None

    def topological_order(self) -> list[V1Operation]:
        """Order operations respecting explicit ``dependencies`` + param refs
        (``ops.NAME`` params imply an edge). Raises on cycles."""
        ops = self.operations or []
        keys = [o.name or f"op-{i}" for i, o in enumerate(ops)]
        by_key = dict(zip(keys, ops))
        deps: dict[str, set[str]] = {}
        for key, o in zip(keys, ops):
            d = set(o.dependencies or [])
            for p in (o.params or {}).values():
                if p.ref and p.ref.startswith("ops."):
                    d.add(p.ref.split(".", 1)[1])
            unknown = d - set(keys)
            if unknown:
                raise ValueError(
                    f"Operation '{key}' depends on unknown operations: {sorted(unknown)}"
                )
            deps[key] = d
        ordered: list[V1Operation] = []
        done: set[str] = set()
        visiting: set[str] = set()

        def visit(key: str) -> None:
            if key in done:
                return
            if key in visiting:
                raise ValueError(f"Cycle detected in dag at operation '{key}'")
            visiting.add(key)
            for d in sorted(deps.get(key, ())):
                visit(d)
            visiting.discard(key)
            done.add(key)
            ordered.append(by_key[key])

        for key in keys:
            visit(key)
        return ordered
