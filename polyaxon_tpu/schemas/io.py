"""Typed inputs/outputs and params (polyflow IO layer).

Equivalent to upstream ``polyaxon._flow.io`` / ``polyaxon._flow.params``
(SURVEY.md §2 "Polyflow schemas"): components declare typed ``inputs`` /
``outputs``; operations bind them with ``params`` whose values may be
literals, references to other runs/ops/dag entities, or context expressions.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Union

from pydantic import Field, field_validator, model_validator

from .base import BaseSchema

# --- IO types (upstream polyaxon `types` registry) -------------------------

IO_TYPES = {
    "any",
    "int",
    "float",
    "bool",
    "str",
    "dict",
    "list",
    "uri",
    "auth",
    "path",
    "file",
    "dockerfile",
    "git",
    "image",
    "event",
    "artifacts",
    "tensorboard",
    "datetime",
    "uuid",
    "md5",
    "sha1",
    "sha256",
}

_PY_TYPES = {
    "int": int,
    "float": (int, float),
    "bool": bool,
    "str": str,
    "dict": dict,
    "list": list,
}

CONTEXT_EXPR = re.compile(r"\{\{\s*(?P<expr>[^}]+?)\s*\}\}")


class V1Validation(BaseSchema):
    """Value constraints for an IO (upstream ``V1Validation``)."""

    delay: Optional[bool] = None
    gt: Optional[float] = None
    ge: Optional[float] = None
    lt: Optional[float] = None
    le: Optional[float] = None
    multiple_of: Optional[float] = None
    min_digits: Optional[int] = None
    max_digits: Optional[int] = None
    decimal_places: Optional[int] = None
    regex: Optional[str] = None
    min_length: Optional[int] = None
    max_length: Optional[int] = None
    contains: Optional[Any] = None
    excludes: Optional[Any] = None
    options: Optional[list[Any]] = None
    min_items: Optional[int] = None
    max_items: Optional[int] = None
    keys: Optional[list[str]] = None
    contains_keys: Optional[list[str]] = None
    excludes_keys: Optional[list[str]] = None

    def check(self, name: str, value: Any) -> None:
        def fail(msg: str) -> None:
            raise ValueError(f"IO '{name}': {msg} (value={value!r})")

        if value is None:
            return
        if self.options is not None and value not in self.options:
            fail(f"value not in options {self.options}")
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            if self.gt is not None and not value > self.gt:
                fail(f"must be > {self.gt}")
            if self.ge is not None and not value >= self.ge:
                fail(f"must be >= {self.ge}")
            if self.lt is not None and not value < self.lt:
                fail(f"must be < {self.lt}")
            if self.le is not None and not value <= self.le:
                fail(f"must be <= {self.le}")
            if self.multiple_of is not None and value % self.multiple_of != 0:
                fail(f"must be a multiple of {self.multiple_of}")
        if isinstance(value, str):
            if self.regex is not None and not re.search(self.regex, value):
                fail(f"does not match regex {self.regex!r}")
            if self.min_length is not None and len(value) < self.min_length:
                fail(f"shorter than minLength {self.min_length}")
            if self.max_length is not None and len(value) > self.max_length:
                fail(f"longer than maxLength {self.max_length}")
        if isinstance(value, (list, tuple)):
            if self.min_items is not None and len(value) < self.min_items:
                fail(f"fewer than minItems {self.min_items}")
            if self.max_items is not None and len(value) > self.max_items:
                fail(f"more than maxItems {self.max_items}")
        if isinstance(value, dict):
            if self.keys is not None and set(value) != set(self.keys):
                fail(f"keys must be exactly {self.keys}")
            if self.contains_keys is not None and not set(self.contains_keys) <= set(value):
                fail(f"must contain keys {self.contains_keys}")
            if self.excludes_keys is not None and set(self.excludes_keys) & set(value):
                fail(f"must not contain keys {self.excludes_keys}")
        if self.contains is not None and isinstance(value, (list, str)) and self.contains not in value:
            fail(f"must contain {self.contains!r}")
        if self.excludes is not None and isinstance(value, (list, str)) and self.excludes in value:
            fail(f"must not contain {self.excludes!r}")


class V1IO(BaseSchema):
    """A typed input or output declaration (upstream ``V1IO``)."""

    name: str
    description: Optional[str] = None
    type: Optional[str] = None
    value: Optional[Any] = None
    is_optional: Optional[bool] = None
    is_list: Optional[bool] = None
    is_flag: Optional[bool] = None
    arg_format: Optional[str] = None
    connection: Optional[str] = None
    to_init: Optional[bool] = None
    to_env: Optional[str] = None
    validation: Optional[V1Validation] = None
    tags: Optional[list[str]] = None

    @field_validator("type")
    @classmethod
    def _check_type(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and v not in IO_TYPES:
            raise ValueError(f"Unknown IO type '{v}'. Valid: {sorted(IO_TYPES)}")
        return v

    def validate_value(self, value: Any) -> Any:
        """Type-check + coerce a bound value against this IO declaration."""
        if value is None:
            if self.value is not None:
                value = self.value
            elif self.is_optional:
                return None
            else:
                raise ValueError(f"Input '{self.name}' is required but no value was provided")
        if isinstance(value, str) and CONTEXT_EXPR.search(value):
            return value  # deferred: resolved at compile time from context
        if self.is_list:
            if not isinstance(value, list):
                raise ValueError(f"Input '{self.name}' expects a list, got {type(value).__name__}")
            items = value
        else:
            items = [value]
        coerced = [self._coerce_one(v) for v in items]
        value = coerced if self.is_list else coerced[0]
        if self.validation:
            self.validation.check(self.name, value)
        return value

    def _coerce_one(self, value: Any) -> Any:
        t = self.type
        if t in (None, "any") or value is None:
            return value
        py = _PY_TYPES.get(t)
        if py is None:
            # uri/path/file/git/... — represented as strings or dicts
            return value
        if t == "bool" and isinstance(value, str):
            low = value.lower()
            if low in ("true", "1", "yes", "y", "on"):
                return True
            if low in ("false", "0", "no", "n", "off"):
                return False
            raise ValueError(f"Input '{self.name}': cannot parse bool from {value!r}")
        if t == "int" and isinstance(value, str):
            return int(value)
        if t == "float" and isinstance(value, str):
            return float(value)
        if t == "float" and isinstance(value, int) and not isinstance(value, bool):
            return float(value)
        if t == "dict" and isinstance(value, str):
            import json

            return json.loads(value)
        if not isinstance(value, py) or (t in ("int", "float") and isinstance(value, bool)):
            raise ValueError(
                f"Input '{self.name}' expects type {t}, got {type(value).__name__}: {value!r}"
            )
        return value

    def as_arg(self, value: Any) -> Optional[str]:
        """Render this IO as a CLI argument (``argFormat``/``isFlag``)."""
        if self.is_flag:
            return f"--{self.name}" if value else None
        if value is None:
            return None
        if self.arg_format:
            return CONTEXT_EXPR.sub(lambda m: str(value), self.arg_format)
        return f"--{self.name}={value}"


class V1Param(BaseSchema):
    """A param binding an operation value to a component input.

    ``ref`` points at another entity (``runs.UUID``, ``ops.NAME``,
    ``dag.inputs``) and ``value`` is then a context expression like
    ``outputs.loss`` resolved against it (upstream ``V1Param``).
    """

    value: Optional[Any] = None
    ref: Optional[str] = None
    connection: Optional[str] = None
    to_init: Optional[bool] = None
    to_env: Optional[str] = None
    context_only: Optional[bool] = None

    @model_validator(mode="after")
    def _check_ref(self) -> "V1Param":
        if self.ref is not None and self.value is None:
            raise ValueError("A param with a 'ref' must set 'value' to an expression on the ref")
        return self


class V1Join(BaseSchema):
    """Fan-in query over upstream runs (upstream ``V1Join``)."""

    query: Optional[str] = None
    sort: Optional[str] = None
    limit: Optional[int] = None
    offset: Optional[int] = None
    params: Optional[dict[str, V1Param]] = None


def validate_params_against_io(
    inputs: Optional[list[V1IO]],
    outputs: Optional[list[V1IO]],
    params: Optional[dict[str, V1Param]],
    matrix_params: Optional[set[str]] = None,
) -> dict[str, Any]:
    """Check an operation's params fully satisfy a component's IO contract.

    ``matrix_params`` are inputs a matrix section will bind per-trial — they
    count as provided at validation time (the tuner fills them in).
    Returns the resolved {name: value} map. Mirrors upstream
    ``ops/params validation`` in ``polyaxon._flow.params``.
    """
    params = params or {}
    matrix_params = matrix_params or set()
    declared = {io.name: io for io in (inputs or [])}
    declared_out = {io.name: io for io in (outputs or [])}
    resolved: dict[str, Any] = {}
    for name, param in params.items():
        if param.context_only:
            continue
        if name not in declared and name not in declared_out:
            raise ValueError(
                f"Param '{name}' was provided but the component declares no such input/output"
            )
    for name, io in declared.items():
        param = params.get(name)
        if param is None and name in matrix_params:
            continue
        if param is not None and param.ref is not None:
            resolved[name] = f"{{{{ {param.ref}.{param.value} }}}}"
            continue
        resolved[name] = io.validate_value(param.value if param is not None else None)
    return resolved
