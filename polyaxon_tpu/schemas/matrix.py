"""Matrix (hyperparameter search) kinds + the hp search-space distributions.

Parity with upstream ``polyaxon._flow.matrix`` (SURVEY.md §2 "Matrix / tuning
kinds"): ``V1GridSearch``, ``V1RandomSearch``, ``V1Hyperband``, ``V1Bayes``,
``V1Hyperopt``, ``V1Mapping``, ``V1Iterative`` plus early-stopping policies.
The actual search algorithms live in ``polyaxon_tpu.hypertune``.
"""

from __future__ import annotations

from typing import Annotated, Any, Literal, Optional, Union

from pydantic import Field, model_validator

from .base import BaseSchema
from .run import V1Tuner

# --- hp distributions -------------------------------------------------------


class V1HpChoice(BaseSchema):
    kind: Literal["choice"] = "choice"
    value: list[Any]


class V1HpPChoice(BaseSchema):
    """Weighted choice: list of [value, probability] pairs."""

    kind: Literal["pchoice"] = "pchoice"
    value: list[Any]

    @model_validator(mode="after")
    def _check(self) -> "V1HpPChoice":
        total = 0.0
        for pair in self.value:
            if not (isinstance(pair, (list, tuple)) and len(pair) == 2):
                raise ValueError("pchoice entries must be [value, prob] pairs")
            total += float(pair[1])
        if abs(total - 1.0) > 1e-6:
            raise ValueError(f"pchoice probabilities must sum to 1, got {total}")
        return self


class V1HpRange(BaseSchema):
    """Discrete range [start, stop, step] (stop exclusive, like Python)."""

    kind: Literal["range"] = "range"
    value: Union[list[Any], dict[str, Any], str]

    def as_tuple(self) -> tuple[float, float, float]:
        v = self.value
        if isinstance(v, str):
            v = [float(x) for x in v.replace(":", ",").split(",")]
        if isinstance(v, dict):
            return float(v["start"]), float(v["stop"]), float(v.get("step", 1))
        if len(v) == 2:
            return float(v[0]), float(v[1]), 1.0
        return float(v[0]), float(v[1]), float(v[2])


class V1HpLinSpace(BaseSchema):
    kind: Literal["linspace"] = "linspace"
    value: Union[list[Any], dict[str, Any], str]

    def as_tuple(self) -> tuple[float, float, int]:
        v = self.value
        if isinstance(v, str):
            v = [float(x) for x in v.replace(":", ",").split(",")]
        if isinstance(v, dict):
            return float(v["start"]), float(v["stop"]), int(v["num"])
        return float(v[0]), float(v[1]), int(v[2])


class V1HpLogSpace(V1HpLinSpace):
    kind: Literal["logspace"] = "logspace"  # type: ignore[assignment]


class V1HpGeomSpace(V1HpLinSpace):
    kind: Literal["geomspace"] = "geomspace"  # type: ignore[assignment]


class _TwoParam(BaseSchema):
    value: Union[list[Any], dict[str, Any]]

    def as_pair(self, a: str, b: str) -> tuple[float, float]:
        v = self.value
        if isinstance(v, dict):
            return float(v[a]), float(v[b])
        return float(v[0]), float(v[1])


class V1HpUniform(_TwoParam):
    kind: Literal["uniform"] = "uniform"


class V1HpQUniform(_TwoParam):
    kind: Literal["quniform"] = "quniform"


class V1HpLogUniform(_TwoParam):
    kind: Literal["loguniform"] = "loguniform"


class V1HpQLogUniform(_TwoParam):
    kind: Literal["qloguniform"] = "qloguniform"


class V1HpNormal(_TwoParam):
    kind: Literal["normal"] = "normal"


class V1HpQNormal(_TwoParam):
    kind: Literal["qnormal"] = "qnormal"


class V1HpLogNormal(_TwoParam):
    kind: Literal["lognormal"] = "lognormal"


class V1HpQLogNormal(_TwoParam):
    kind: Literal["qlognormal"] = "qlognormal"


class V1HpDateRange(BaseSchema):
    kind: Literal["daterange"] = "daterange"
    value: list[Any]


class V1HpDateTimeRange(BaseSchema):
    kind: Literal["datetimerange"] = "datetimerange"
    value: list[Any]


HpUnion = Annotated[
    Union[
        V1HpChoice, V1HpPChoice, V1HpRange, V1HpLinSpace, V1HpLogSpace,
        V1HpGeomSpace, V1HpUniform, V1HpQUniform, V1HpLogUniform,
        V1HpQLogUniform, V1HpNormal, V1HpQNormal, V1HpLogNormal,
        V1HpQLogNormal, V1HpDateRange, V1HpDateTimeRange,
    ],
    Field(discriminator="kind"),
]

# Distributions a grid search can enumerate exhaustively.
GRID_KINDS = {"choice", "range", "linspace", "logspace", "geomspace"}


# --- early stopping ---------------------------------------------------------


class V1MetricEarlyStopping(BaseSchema):
    kind: Literal["metric_early_stopping"] = "metric_early_stopping"
    metric: str
    value: float
    optimization: str = "maximize"  # maximize | minimize
    policy: Optional[dict[str, Any]] = None


class V1FailureEarlyStopping(BaseSchema):
    kind: Literal["failure_early_stopping"] = "failure_early_stopping"
    percent: float


EarlyStoppingUnion = Annotated[
    Union[V1MetricEarlyStopping, V1FailureEarlyStopping],
    Field(discriminator="kind"),
]


class V1OptimizationMetric(BaseSchema):
    name: str
    optimization: str = "maximize"

    @property
    def maximize(self) -> bool:
        return self.optimization.lower() == "maximize"


class V1OptimizationResource(BaseSchema):
    """The budget resource Hyperband rations (e.g. training epochs/steps)."""

    name: str
    type: str = "int"

    def cast(self, v: float) -> Union[int, float]:
        return int(v) if self.type == "int" else float(v)


# --- matrix kinds -----------------------------------------------------------


class _BaseSearch(BaseSchema):
    params: dict[str, HpUnion]
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStoppingUnion]] = None
    tuner: Optional[V1Tuner] = None
    # Parent TPU slice the sweep packs trials onto (alias "v5e-256" or bare
    # topology "16x16" in the trial's accelerator). With a tpujob component
    # the tuner assigns each concurrency slot a disjoint sub-slice of this
    # parent (BASELINE config 5: 16 ViT trials on one v5e-256).
    slice: Optional[str] = None


class V1Mapping(BaseSchema):
    """Explicit list of param dicts to fan out (upstream ``V1Mapping``)."""

    kind: Literal["mapping"] = "mapping"
    values: list[dict[str, Any]]
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStoppingUnion]] = None
    slice: Optional[str] = None  # parent TPU slice for sub-slice packing


class V1GridSearch(_BaseSearch):
    kind: Literal["grid"] = "grid"
    num_runs: Optional[int] = None

    @model_validator(mode="after")
    def _gridable(self) -> "V1GridSearch":
        for name, hp in self.params.items():
            if hp.kind not in GRID_KINDS:
                raise ValueError(
                    f"Grid search param '{name}' uses non-enumerable distribution "
                    f"'{hp.kind}'; use random/bayes/hyperband instead"
                )
        return self


class V1RandomSearch(_BaseSearch):
    kind: Literal["random"] = "random"
    num_runs: int
    seed: Optional[int] = None


class V1Hyperband(_BaseSearch):
    """Hyperband successive halving (Li et al. 2018). Bracket math in
    ``hypertune.hyperband`` mirrors the paper: s_max = floor(log_eta(R)),
    n_i/r_i per rung; upstream ``V1Hyperband``.

    ``asynchronous: true`` switches to ASHA (Li et al., MLSys 2020): one
    bracket, rungs promote the moment they have a top-1/eta candidate, new
    base configs fill idle slots — no rung barriers, so a straggler trial
    never idles the other packed sub-slices (VERDICT r3 #5). ``num_runs``
    caps the base-rung configs ASHA samples (default eta**s_max, the width
    of synchronous Hyperband's most exploratory bracket)."""

    kind: Literal["hyperband"] = "hyperband"
    max_iterations: int
    eta: int = 3
    resource: V1OptimizationResource
    metric: V1OptimizationMetric
    resume: Optional[bool] = None
    seed: Optional[int] = None
    asynchronous: Optional[bool] = None
    num_runs: Optional[int] = None  # ASHA base-config budget


class V1Bayes(_BaseSearch):
    """Bayesian optimization with a GP surrogate (upstream ``V1Bayes``)."""

    kind: Literal["bayes"] = "bayes"
    num_initial_runs: int
    max_iterations: int
    metric: V1OptimizationMetric
    utility_function: Optional[dict[str, Any]] = None  # {acquisitionFunction, kappa, eps, gamma, numWarmup, numSamples}
    seed: Optional[int] = None


class V1Hyperopt(_BaseSearch):
    """TPE/rand/anneal via a hyperopt-compatible bridge (upstream ``V1Hyperopt``)."""

    kind: Literal["hyperopt"] = "hyperopt"
    algorithm: str = "tpe"  # tpe | rand | anneal
    num_runs: int
    max_iterations: Optional[int] = None
    metric: V1OptimizationMetric
    seed: Optional[int] = None


class V1Iterative(_BaseSearch):
    """User-driven iterative tuning loop (upstream ``V1Iterative``)."""

    kind: Literal["iterative"] = "iterative"
    max_iterations: int
    seed: Optional[int] = None


class V1Pbt(_BaseSearch):
    """Population based training (Jaderberg et al. 2017; ISSUE 19).

    A population of ``population`` members trains in generations of
    ``resource`` each. After a member finishes a generation, exploit
    compares it to its cohort: a bottom-``quartile`` member abandons its
    weights, forks a top-``quartile`` survivor's checkpoint
    (``parent_trial`` in the child's meta; the runtime restores it via
    ``Checkpointer.restore_raw`` + ``init_state_from`` — PR-13's fork
    machinery), and explore perturbs the survivor's hyperparameters
    (numeric hps ×/÷ ``perturb_factor``, choices resampled with
    ``resample_prob``). Survivors continue from their own checkpoints
    with params unchanged. All draws are seeded per
    ``(sweep_uuid, member, generation)`` so an adopted population
    replays its exploit/explore decisions deterministically."""

    kind: Literal["pbt"] = "pbt"
    population: int
    num_generations: int
    # resource units each trial trains per generation (named like the
    # other kinds' total budget; here the generation IS the unit of work)
    max_iterations: int
    resource: V1OptimizationResource
    metric: V1OptimizationMetric
    quartile: float = 0.25
    perturb_factor: float = 1.2
    resample_prob: float = 0.25
    seed: Optional[int] = None


MatrixUnion = Annotated[
    Union[
        V1Mapping, V1GridSearch, V1RandomSearch, V1Hyperband,
        V1Bayes, V1Hyperopt, V1Iterative, V1Pbt,
    ],
    Field(discriminator="kind"),
]
