"""``V1Component`` — the unit of reusable work (upstream ``V1Component``,
SURVEY.md §2 "Polyflow schemas")."""

from __future__ import annotations

from typing import Any, Optional

from pydantic import field_validator

from .base import BaseSchema
from .io import V1IO
from .lifecycle import V1Build, V1Cache, V1Hook, V1Plugins, V1Termination
from .run import RunUnion

_RUN_ADAPTER = None


def _run_union_adapter():
    """Module-cached TypeAdapter(RunUnion): building the adapter walks and
    simplifies the whole discriminated-union core schema (~35 ms) — per
    CALL that was the single largest cost of compiling or scheduling a run
    (2× resolve per run = ~70 ms of pure schema rebuild on the agent's hot
    path, see docs/PERFORMANCE.md "Control-plane performance"). Validation
    itself is microseconds."""
    global _RUN_ADAPTER
    if _RUN_ADAPTER is None:
        from pydantic import TypeAdapter

        _RUN_ADAPTER = TypeAdapter(RunUnion)
    return _RUN_ADAPTER

SPEC_VERSION = 1.1


class V1Component(BaseSchema):
    version: Optional[float] = None
    kind: Optional[str] = None  # "component"
    name: Optional[str] = None
    description: Optional[str] = None
    tags: Optional[list[str]] = None
    presets: Optional[list[str]] = None
    queue: Optional[str] = None
    cache: Optional[V1Cache] = None
    termination: Optional[V1Termination] = None
    plugins: Optional[V1Plugins] = None
    build: Optional[V1Build] = None
    hooks: Optional[list[V1Hook]] = None
    inputs: Optional[list[V1IO]] = None
    outputs: Optional[list[V1IO]] = None
    run: Optional[Any] = None  # RunUnion or V1Dag (validated below)
    template: Optional[dict[str, Any]] = None
    is_approved: Optional[bool] = None
    cost: Optional[float] = None

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: Optional[str]) -> Optional[str]:
        if v is not None and v != "component":
            raise ValueError(f"Component kind must be 'component', got '{v}'")
        return v

    @field_validator("run", mode="before")
    @classmethod
    def _validate_run(cls, v: Any) -> Any:
        if v is None or not isinstance(v, dict):
            return v
        kind = v.get("kind")
        if kind == "dag":
            from .dag import V1Dag

            return V1Dag.from_dict(v)
        if kind == "tuner":
            from .run import V1Tuner

            return V1Tuner.from_dict({k: x for k, x in v.items() if k != "kind"})
        return _run_union_adapter().validate_python(v)

    def get_run_kind(self) -> Optional[str]:
        if self.run is None:
            return None
        return getattr(self.run, "kind", None)

    def get_io(self, name: str) -> Optional[V1IO]:
        for io in (self.inputs or []) + (self.outputs or []):
            if io.name == name:
                return io
        return None

    def validate(self) -> None:
        if self.run is None:
            raise ValueError("Component requires a 'run' section")
        names = [io.name for io in (self.inputs or []) + (self.outputs or [])]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(f"Duplicate IO names in component: {sorted(dupes)}")
