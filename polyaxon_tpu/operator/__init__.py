"""L3 operator: Operation reconciler over a Cluster backend.

The TPU-native equivalent of upstream's Go operator (SURVEY.md §2
"Operator"): the decision kernel is native C++ (native/reconcile_core.cc,
loaded via ctypes), the effectful shell is Python, and the cluster is
pluggable — FakeCluster (subprocess pods) for local/e2e, a real K8s client
later.
"""

from .cluster import Cluster, FakeCluster, PodPhase, PodStatus
from .kube import KubeApiError, KubeCluster
from .native import (
    Action,
    Decision,
    Observed,
    Reason,
    reconcile,
    reconcile_native,
    reconcile_python,
)
from .reconciler import OperationCR, OperationReconciler

__all__ = [
    "Action",
    "Cluster",
    "Decision",
    "FakeCluster",
    "KubeApiError",
    "KubeCluster",
    "Observed",
    "OperationCR",
    "OperationReconciler",
    "PodPhase",
    "PodStatus",
    "Reason",
    "reconcile",
    "reconcile_native",
    "reconcile_python",
]
