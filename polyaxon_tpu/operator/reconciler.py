"""Operation reconciler — the L3 operator loop.

Upstream: a Go controller-runtime reconciler on the ``Operation`` CRD that
creates pods/Jobs, watches child status, patches the CR, and enforces
TTL/termination (SURVEY.md §2 "Operator" row, §3a steps 4-6). Here the same
loop runs over a ``Cluster`` backend: manifests in (rendered by the compiler
— rendering stays in Python per SURVEY.md §7 hard part (d)), status
callbacks out. Decisions are made by the native C++ kernel
(native/reconcile_core.cc) from observed pod phases only, so the loop itself
is trivially idempotent — the controller pattern's level-triggered contract.
"""

from __future__ import annotations

import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..schemas.statuses import V1Statuses
from .cluster import Cluster, PodPhase
from .native import Action, Decision, Observed, Reason, reconcile


@dataclass
class OperationCR:
    """The 'custom resource': everything the operator needs about one run."""

    run_uuid: str
    resources: list[dict]
    backoff_limit: int = 0
    active_deadline_s: float = 0.0  # <=0: none
    ttl_s: float = -1.0             # <0: keep resources after finish
    # per-pod restart (ISSUE 12): replicated services replace ONLY the
    # failed replica pod (the survivors keep serving their in-flight
    # requests) instead of the slice-level all-or-nothing teardown a
    # collective training job needs. Budget still comes from
    # backoff_limit; past it the kernel's FAIL path takes over.
    per_pod_restart: bool = False

    @property
    def label_selector(self) -> dict[str, str]:
        return {"app.polyaxon.com/run": self.run_uuid}


@dataclass
class _OpState:
    op: OperationCR
    applied_at: float = field(default_factory=time.monotonic)
    retries_done: int = 0
    was_running: bool = False
    finished_at: Optional[float] = None
    final_status: Optional[str] = None
    gc_done: bool = False
    applying: bool = True  # manifests not yet fully applied; reconcile must WAIT
    exhausted_fired: bool = False  # on_retry_exhausted exactly-once latch


# status callback: (run_uuid, status, message)
StatusFn = Callable[[str, str, Optional[str]], None]

_REASON_MSG = {
    Reason.DEADLINE: "activeDeadlineSeconds exceeded",
    Reason.POD_FAILED: "pod failed; no retries left",
    Reason.COMPLETED: None,
    Reason.TTL: "ttl expired",
}


class OperationReconciler:
    def __init__(self, cluster: Cluster, on_status: Optional[StatusFn] = None,
                 retry=None, on_status_many=None, on_retry_exhausted=None):
        from ..resilience.retry import RetryPolicy

        self.cluster = cluster
        # Cluster verbs ride through a transient-failure retry: a 5xx/429/
        # timeout burst mid-RESTART must not strand an op between "pods
        # deleted" and "pods re-applied" (it would burn the whole backoff
        # budget on API weather, not slice failures). Bounded tighter than
        # the HTTP default so a reconcile pass can't stall for long.
        self.retry: RetryPolicy = retry if retry is not None else RetryPolicy(
            max_attempts=4, base_delay=0.1, max_delay=2.0, deadline=8.0)
        self.on_status = on_status or (lambda *a: None)
        # optional batch form: [(uuid, status, message), ...] applied as one
        # store transaction (the agent wires Store.transition_many). Multi-
        # step edges (restart's 4-transition walk) use it when available.
        self.on_status_many = on_status_many or (
            lambda updates: [self.on_status(*u) for u in updates])
        # observability hook (ISSUE 5): fired when an op FAILs with a
        # non-zero backoff budget fully burned — the agent wires the
        # shared retry-exhaustion counter here
        self.on_retry_exhausted = on_retry_exhausted or (lambda: None)
        self._ops: dict[str, _OpState] = {}
        self._lock = threading.Lock()
        self._reconcile_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- CR lifecycle ------------------------------------------------------

    def apply(self, op: OperationCR) -> None:
        """Create the operation's resources and start tracking it.

        The op is registered first (so a concurrent apply of the same uuid
        errors) but held in ``applying`` state until every manifest is on the
        cluster: a background reconcile pass between per-manifest applies
        must not observe a partial pod set — e.g. every applied pod already
        succeeded — and emit a premature SUCCEED."""
        with self._lock:
            if op.run_uuid in self._ops:
                raise ValueError(f"operation {op.run_uuid} already applied")
            state = _OpState(op=op)
            self._ops[op.run_uuid] = state
        try:
            for manifest in op.resources:
                self._c(self.cluster.apply, manifest)
        except Exception:
            # tear down BEFORE freeing the uuid so a concurrent re-apply
            # can't register (and create pods) that this rollback would then
            # delete; swallow teardown errors so the apply error propagates
            try:
                self._c(self.cluster.delete_selected, op.label_selector)
            except Exception:
                pass
            with self._lock:
                if self._ops.get(op.run_uuid) is state:
                    del self._ops[op.run_uuid]
            raise
        with self._lock:
            if self._ops.get(op.run_uuid) is not state:
                # concurrent delete() mid-apply untracked us after removing
                # the pods applied so far; remove the ones applied since
                concurrent_delete = True
            else:
                state.applied_at = time.monotonic()
                state.applying = False
                concurrent_delete = False
        if concurrent_delete:
            self._c(self.cluster.delete_selected, op.label_selector)

    def adopt(self, op: OperationCR, elapsed_s: float = 0.0,
              retries_done: int = 0) -> bool:
        """Re-track an operation whose pods may already exist (agent
        restart recovery): if any pods match the selector, track WITHOUT
        re-applying — the next reconcile pass observes them as usual; if
        none exist (cluster lost them too), fall back to a fresh apply.

        ``elapsed_s`` backdates the deadline clock (the run's wall time so
        far, from the store's started_at) and ``retries_done`` restores the
        backoff budget already burned — otherwise every agent restart would
        reset activeDeadlineSeconds/backoff_limit to zero.
        Returns True when existing pods were adopted."""
        existing = self._c(self.cluster.pod_statuses, op.label_selector)
        # Terminating pods are not adoptable: K8s DELETE returns before
        # etcd removal, so a just-deleted set still lists — adopting it
        # would re-track pods that die moments later and read as a slice
        # failure that never happened (burning a retry attempt).
        existing = [s for s in existing if not s.terminating]
        if not existing:
            self.apply(op)
            return False
        with self._lock:
            if op.run_uuid in self._ops:
                raise ValueError(f"operation {op.run_uuid} already tracked")
            state = _OpState(op=op)
            state.applied_at = time.monotonic() - max(elapsed_s, 0.0)
            state.applying = False
            state.retries_done = int(retries_done)
            self._ops[op.run_uuid] = state
        return True

    def scale(self, run_uuid: str, resources: list[dict],
              keep: Optional[set] = None) -> tuple[int, int]:
        """Converge a tracked operation's pod set onto ``resources``
        (service replica autoscale, ISSUE 9): diff DESIRED pod names
        against the LIVE set — apply the missing, delete the surplus —
        and swap the op's resources so restarts re-apply the new target.

        Diffing against live pods (not the previously-recorded resources)
        makes the verb self-healing: surplus pods left by a crash mid-
        scale-down are deleted by the next scale call, and a pod name
        already live is never re-applied (zero duplicate launches — a
        duplicate apply would 409 like a real apiserver). Returns
        (applied, deleted).

        ``keep`` (ISSUE 12, graceful drain): surplus pod names that are
        still DRAINING — they stay off the desired set (restarts won't
        re-apply them) but are NOT deleted this pass; the agent calls
        scale again without ``keep`` once their drain completed or timed
        out, so a surplus pod is only ever deleted after its in-flight
        requests finished (or the drain deadline passed)."""
        with self._lock:
            state = self._ops.get(run_uuid)
        if state is None:
            raise KeyError(f"operation {run_uuid} is not tracked")
        if state.final_status is not None:
            return (0, 0)
        # serialize with reconcile passes: an observe between our deletes
        # and applies must not misread the half-converged set
        with self._reconcile_lock:
            desired = {m["metadata"]["name"]: m for m in resources
                       if m.get("kind") == "Pod"}
            live = {}
            for s in self._c(self.cluster.pod_statuses,
                             state.op.label_selector):
                live[s.name] = s
            applied = deleted = 0
            protected = set(keep or ())
            for name, st in live.items():
                if (name not in desired and not st.terminating
                        and name not in protected):
                    self._c(self.cluster.delete, "Pod", name)
                    deleted += 1
            for name, manifest in desired.items():
                if name in live:
                    continue  # already live (or Terminating: next pass)
                self._c(self.cluster.apply, manifest)
                applied += 1
            state.op.resources = resources
        return applied, deleted

    def delete(self, run_uuid: str) -> None:
        """Stop tracking and tear down resources (stop / user delete)."""
        with self._lock:
            state = self._ops.pop(run_uuid, None)
        if state:
            self._c(self.cluster.delete_selected, state.op.label_selector)

    def untrack(self, run_uuid: str) -> None:
        """Forget an operation WITHOUT touching its pods — shard handoff
        (ISSUE 6): a demoted shard's runs belong to the new owner, which
        adopts the live pod set; deleting here would kill it out from
        under the adopter."""
        with self._lock:
            self._ops.pop(run_uuid, None)

    def is_tracked(self, run_uuid: str) -> bool:
        with self._lock:
            return run_uuid in self._ops

    def tracked_uuids(self) -> set:
        with self._lock:
            return set(self._ops)

    def active_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._ops.values() if s.final_status is None)

    def final_status(self, run_uuid: str) -> Optional[str]:
        with self._lock:
            state = self._ops.get(run_uuid)
        return state.final_status if state else None

    # -- the reconcile loop ------------------------------------------------

    def reconcile_once(self) -> None:
        # serialized: both the agent poll loop and the (kube) watch thread
        # call this; concurrent passes would double-count a failure's
        # retry or race a restart's delete against its re-apply
        with self._reconcile_lock:
            with self._lock:
                states = list(self._ops.values())
            for state in states:
                try:
                    self._reconcile_op(state)
                except Exception:
                    traceback.print_exc()

    def _c(self, fn, *args):
        """Run one cluster verb through the transient-failure retry."""
        return self.retry.call(fn, *args)

    def _observe(self, state: _OpState) -> Observed:
        statuses = self._c(self.cluster.pod_statuses, state.op.label_selector)
        counts = {phase: 0 for phase in PodPhase}
        for s in statuses:
            counts[s.phase] += 1
        now = time.monotonic()
        return Observed(
            pods_total=len(statuses),
            pending=counts[PodPhase.PENDING],
            running=counts[PodPhase.RUNNING],
            succeeded=counts[PodPhase.SUCCEEDED],
            failed=counts[PodPhase.FAILED],
            retries_done=state.retries_done,
            backoff_limit=state.op.backoff_limit,
            is_finished=state.final_status is not None,
            was_running=state.was_running,
            elapsed_s=now - state.applied_at,
            finished_for_s=(now - state.finished_at) if state.finished_at else 0.0,
            active_deadline_s=state.op.active_deadline_s,
            ttl_s=state.op.ttl_s,
        )

    def _replace_failed_pods(self, state: _OpState) -> bool:
        """Per-pod restart (ISSUE 12): a replicated service replaces ONLY
        its failed replica pods — deleting the whole set would abort the
        surviving replicas' in-flight requests to heal one. Each
        replacement round burns one backoff attempt (same budget as a
        slice restart); once the budget is gone the kernel's POD_FAILED
        path fails the op as usual. Run status is untouched: the service
        is still running through its surviving replicas — replica churn
        is a pod-level event, not a run transition."""
        statuses = self._c(self.cluster.pod_statuses,
                           state.op.label_selector)
        failed = [s for s in statuses
                  if s.phase == PodPhase.FAILED and not s.terminating]
        if not failed:
            return False
        if state.retries_done >= state.op.backoff_limit:
            return False  # budget gone: the kernel fails the op
        state.retries_done += 1
        desired = {m["metadata"]["name"]: m for m in state.op.resources
                   if m.get("kind") == "Pod"}
        for s in failed:
            self._c(self.cluster.delete, "Pod", s.name)
            manifest = desired.get(s.name)
            if manifest is not None:
                self._c(self.cluster.apply, manifest)
            # a failed pod no longer in the desired set (died mid-drain)
            # is simply cleaned up, never resurrected
        return True

    def _reconcile_op(self, state: _OpState) -> None:
        if state.gc_done or state.applying:
            return
        if state.op.per_pod_restart and state.final_status is None:
            if self._replace_failed_pods(state):
                return
        decision: Decision = reconcile(self._observe(state))
        op = state.op
        if decision.action == Action.WAIT:
            return
        if decision.action == Action.SET_RUNNING:
            # report FIRST: if the store write fails (outage weather),
            # was_running stays False and the next level-triggered pass
            # re-emits — otherwise the terminal batch would later skip its
            # RUNNING prelude and the scheduled->succeeded edge would be
            # silently rejected by the status machine (ISSUE 7)
            self.on_status(op.run_uuid, V1Statuses.RUNNING.value, None)
            state.was_running = True
            return
        if decision.action == Action.RESTART:
            # slice-level all-or-nothing: tear down every pod, re-apply all.
            # Pods that fail faster than one observe interval were still
            # running — emit RUNNING first so the status machine accepts the
            # RETRYING edge (running->retrying; scheduled->retrying is not
            # a legal transition). The whole 4-step walk is one batch.
            state.retries_done += 1
            updates = []
            if not state.was_running:
                updates.append((op.run_uuid, V1Statuses.RUNNING.value, None))
            updates += [
                (op.run_uuid, V1Statuses.RETRYING.value,
                 f"attempt {state.retries_done + 1}/{op.backoff_limit + 1}"),
                (op.run_uuid, V1Statuses.QUEUED.value, None),
                (op.run_uuid, V1Statuses.SCHEDULED.value, None),
            ]
            try:
                self.on_status_many(updates)
            except Exception:
                # store outage mid-edge: nothing was deleted/re-applied
                # yet — give the attempt back so the retry budget pays
                # for slice failures, never for store weather
                state.retries_done -= 1
                raise
            self._c(self.cluster.delete_selected, op.label_selector)
            for manifest in op.resources:
                self._c(self.cluster.apply, manifest)
            state.applied_at = time.monotonic()
            state.was_running = False
            return
        if decision.action in (Action.FAIL, Action.SUCCEED):
            status = (V1Statuses.SUCCEEDED if decision.action == Action.SUCCEED
                      else V1Statuses.FAILED)
            updates = []
            if decision.action == Action.SUCCEED and not state.was_running:
                # pods ran to completion between observe passes; the status
                # machine has no scheduled->succeeded edge, so record the
                # (true) running phase first
                updates.append((op.run_uuid, V1Statuses.RUNNING.value, None))
            state.final_status = status.value
            state.finished_at = time.monotonic()
            if (decision.action == Action.FAIL
                    and decision.reason == Reason.POD_FAILED
                    and op.backoff_limit > 0 and not state.exhausted_fired):
                # exactly-once via its own latch (not final_status: that
                # one UNLATCHES below when the store write fails, and the
                # re-emit must not double-count the exhaustion)
                state.exhausted_fired = True
                try:
                    self.on_retry_exhausted()
                except Exception:
                    traceback.print_exc()
            # report BEFORE any teardown so on_status consumers (agent log
            # scraping) still see the pods; then failure tears them down,
            # success leaves them until TTL (or forever when ttl < 0)
            updates.append(
                (op.run_uuid, status.value, _REASON_MSG.get(decision.reason)))
            try:
                self.on_status_many(updates)
            except Exception:
                # the store write failed (outage weather, NOT a fencing
                # rejection — the agent's callbacks swallow those): UNLATCH
                # so the next level-triggered pass re-derives this exact
                # decision from the still-live pods and re-emits. A store
                # outage must never eat a terminal transition (ISSUE 7).
                state.final_status = None
                state.finished_at = None
                raise
            if decision.action == Action.FAIL or op.ttl_s == 0:
                self._c(self.cluster.delete_selected, op.label_selector)
                if op.ttl_s == 0:
                    state.gc_done = True
            return
        if decision.action == Action.GC:
            self._c(self.cluster.delete_selected, op.label_selector)
            state.gc_done = True
            return

    # -- background watch --------------------------------------------------

    def start(self, interval: float = 0.2) -> "OperationReconciler":
        def _loop():
            while not self._stop.wait(interval):
                self.reconcile_once()

        self._thread = threading.Thread(target=_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
