"""ctypes bridge to the C++ reconcile kernel, with a pure-Python mirror.

The .so is built on demand with g++ (no pybind11 in the image — C ABI +
ctypes per the environment constraints) and cached next to the source. The
Python mirror exists for toolchain-less environments and as the parity
oracle in tests: both implementations MUST make identical decisions.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass
from enum import IntEnum


class Action(IntEnum):
    WAIT = 0
    SET_RUNNING = 1
    RESTART = 2
    FAIL = 3
    SUCCEED = 4
    GC = 5


class Reason(IntEnum):
    NONE = 0
    DEADLINE = 1
    POD_FAILED = 2
    COMPLETED = 3
    TTL = 4
    BACKOFF = 5


@dataclass
class Observed:
    pods_total: int
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    retries_done: int = 0
    backoff_limit: int = 0
    is_finished: bool = False
    was_running: bool = False
    elapsed_s: float = 0.0
    finished_for_s: float = 0.0
    active_deadline_s: float = 0.0  # <=0: none
    ttl_s: float = -1.0             # <0: none


@dataclass
class Decision:
    action: Action
    reason: Reason


class _CObserved(ctypes.Structure):
    _fields_ = [
        ("pods_total", ctypes.c_int32),
        ("pending", ctypes.c_int32),
        ("running", ctypes.c_int32),
        ("succeeded", ctypes.c_int32),
        ("failed", ctypes.c_int32),
        ("retries_done", ctypes.c_int32),
        ("backoff_limit", ctypes.c_int32),
        ("is_finished", ctypes.c_int32),
        ("was_running", ctypes.c_int32),
        ("elapsed_s", ctypes.c_double),
        ("finished_for_s", ctypes.c_double),
        ("active_deadline_s", ctypes.c_double),
        ("ttl_s", ctypes.c_double),
    ]


class _CDecision(ctypes.Structure):
    _fields_ = [("action", ctypes.c_int32), ("reason", ctypes.c_int32)]


_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native", "reconcile_core.cc")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native", "_build", "libplxreconcile.so")
_build_lock = threading.Lock()
_lib = None
_lib_tried = False


def _build_so() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load_native():
    """Load (building if needed) the C++ kernel; None when unavailable."""
    global _lib, _lib_tried
    with _build_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        try:
            stale = (not os.path.exists(_SO)
                     or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        except OSError:
            # e.g. .so present but source missing (packaged install): use the
            # existing binary as-is; any load failure below falls back to the
            # Python mirror
            stale = not os.path.exists(_SO)
        if stale:
            if not _build_so():
                return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.plx_reconcile.argtypes = [ctypes.POINTER(_CObserved), ctypes.POINTER(_CDecision)]
            lib.plx_reconcile.restype = ctypes.c_int32
            lib.plx_abi_version.restype = ctypes.c_int32
            if lib.plx_abi_version() != 1:
                return None
            _lib = lib
        except OSError:
            return None
        return _lib


def reconcile_native(obs: Observed) -> Decision:
    lib = load_native()
    if lib is None:
        raise RuntimeError("native reconcile kernel unavailable")
    c_obs = _CObserved(
        pods_total=obs.pods_total, pending=obs.pending, running=obs.running,
        succeeded=obs.succeeded, failed=obs.failed,
        retries_done=obs.retries_done, backoff_limit=obs.backoff_limit,
        is_finished=int(obs.is_finished), was_running=int(obs.was_running),
        elapsed_s=obs.elapsed_s, finished_for_s=obs.finished_for_s,
        active_deadline_s=obs.active_deadline_s, ttl_s=obs.ttl_s,
    )
    out = _CDecision()
    rc = lib.plx_reconcile(ctypes.byref(c_obs), ctypes.byref(out))
    if rc != 0:
        raise ValueError(f"plx_reconcile rejected input (rc={rc}): {obs}")
    return Decision(Action(out.action), Reason(out.reason))


def reconcile_python(obs: Observed) -> Decision:
    """Pure-Python mirror of reconcile_core.cc (same priority order)."""
    if min(obs.pods_total, obs.pending, obs.running, obs.succeeded, obs.failed) < 0:
        raise ValueError(f"negative pod counts: {obs}")
    if obs.is_finished:
        if obs.ttl_s >= 0.0 and obs.finished_for_s >= obs.ttl_s:
            return Decision(Action.GC, Reason.TTL)
        return Decision(Action.WAIT, Reason.NONE)
    if obs.active_deadline_s > 0.0 and obs.elapsed_s > obs.active_deadline_s:
        return Decision(Action.FAIL, Reason.DEADLINE)
    # a failed pod, OR a slice whose pods vanished wholesale after it was
    # running (node GC, external delete): both are slice loss — restart
    # whole within budget, else fail. Without the vanished-pods arm the
    # operation would WAIT forever on an empty pod set.
    if obs.failed > 0 or (obs.pods_total == 0 and obs.was_running):
        if obs.retries_done < obs.backoff_limit:
            return Decision(Action.RESTART, Reason.BACKOFF)
        return Decision(Action.FAIL, Reason.POD_FAILED)
    if obs.pods_total > 0 and obs.succeeded == obs.pods_total:
        return Decision(Action.SUCCEED, Reason.COMPLETED)
    if obs.running > 0 and not obs.was_running:
        return Decision(Action.SET_RUNNING, Reason.NONE)
    return Decision(Action.WAIT, Reason.NONE)


def reconcile(obs: Observed) -> Decision:
    """Native kernel when buildable, Python mirror otherwise."""
    if load_native() is not None:
        return reconcile_native(obs)
    return reconcile_python(obs)
