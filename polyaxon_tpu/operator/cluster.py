"""Cluster backends for the operator.

``Cluster`` is the minimal surface the reconciler needs (apply/delete/
observe/logs) — the shape of the K8s REST verbs upstream's Go operator used
through controller-runtime (SURVEY.md §2 "Operator" row), kept abstract so a
real K8s backend can slot in without touching the reconciler.

``FakeCluster`` is the in-proc test cluster SURVEY.md §4 prescribes ("fake
'cluster' = in-proc scheduler + subprocess pods"): every applied Pod manifest
becomes a real subprocess with the manifest's env, headless-Service DNS names
rewritten to loopback so multi-"host" rendezvous genuinely works on one
machine.
"""

from __future__ import annotations

import os
import re
import subprocess
import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional


class PodPhase(str, Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class PodStatus:
    name: str
    phase: PodPhase
    exit_code: Optional[int] = None
    message: Optional[str] = None
    # deletionTimestamp set: the pod is on its way out (K8s DELETE is
    # async). Adoption/resync must not treat such a pod as a live member
    # of the slice — it will vanish moments later.
    terminating: bool = False


class Cluster(ABC):
    """What the reconciler needs from a cluster."""

    @abstractmethod
    def apply(self, manifest: dict) -> None: ...

    @abstractmethod
    def delete(self, kind: str, name: str) -> None: ...

    @abstractmethod
    def delete_selected(self, label_selector: dict[str, str]) -> None:
        """Delete every pod + service matching the selector (the
        reconciler's teardown verb: restart, fail, stop, TTL-GC)."""

    @abstractmethod
    def pod_statuses(self, label_selector: dict[str, str]) -> list[PodStatus]: ...

    @abstractmethod
    def pod_logs(self, name: str) -> str: ...

    def service_host(self, name: str) -> str:
        """Host a Service's declared port is reachable at from the agent's
        vantage point — feeds ``polyaxon_tpu port-forward``. FakeCluster
        pods are loopback processes binding their declared ports directly;
        a real cluster resolves the Service DNS name."""
        return "127.0.0.1"

    def run_pods(self, label_key: str = "app.polyaxon.com/run",
                 ) -> dict[str, list[PodStatus]]:
        """ONE listing of every framework pod, grouped by run uuid (the
        ``label_key`` value) — the agent's cold-start resync uses this to
        classify every in-flight run with a single cluster call instead of
        one ``pod_statuses`` per run. Backends without a grouped listing
        may raise ``NotImplementedError``; the resync falls back to
        per-run queries."""
        raise NotImplementedError


def _match_labels(manifest: dict, selector: dict[str, str]) -> bool:
    """K8s-style equality selectors; a ``None`` value means key-existence
    (same contract as ``KubeCluster._selector``)."""
    labels = (manifest.get("metadata") or {}).get("labels") or {}
    return all(k in labels if v is None else labels.get(k) == v
               for k, v in selector.items())


@dataclass
class _FakePod:
    manifest: dict
    proc: Optional[subprocess.Popen] = None
    log_path: str = ""
    started_at: float = field(default_factory=time.monotonic)
    forced_phase: Optional[PodPhase] = None  # tests / no-op pods

    @property
    def name(self) -> str:
        return self.manifest["metadata"]["name"]

    def phase(self) -> PodStatus:
        if self.forced_phase is not None:
            return PodStatus(self.name, self.forced_phase)
        if self.proc is None:
            return PodStatus(self.name, PodPhase.PENDING)
        rc = self.proc.poll()
        if rc is None:
            return PodStatus(self.name, PodPhase.RUNNING)
        if rc == 0:
            return PodStatus(self.name, PodPhase.SUCCEEDED, exit_code=0)
        return PodStatus(self.name, PodPhase.FAILED, exit_code=rc,
                         message=f"exit code {rc}")


class FakeCluster(Cluster):
    """Runs Pod manifests as local subprocesses; records Services.

    DNS: pods in a real cluster reach each other via
    ``<hostname>.<subdomain>`` headless-service names. Locally every "host"
    is a process on loopback, so any env value referencing a registered
    Service domain is rewritten to ``127.0.0.1`` — jax.distributed rendezvous
    then works unmodified across the fake hosts.
    """

    def __init__(self, workdir: str):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.pods: dict[str, _FakePod] = {}
        self.services: dict[str, dict] = {}
        self.service_ports: dict[str, int] = {}
        self._lock = threading.Lock()
        # observability for tests: every env block a pod was launched with
        self.launched_env: dict[str, dict[str, str]] = {}
        # launch-attempt audit (ISSUE 4): every accepted Pod apply counts
        # against its run label; an apply for a pod name that is still
        # live is a DUPLICATE launch — the exact bug agent crash-safety
        # must rule out — recorded here and rejected (a real apiserver
        # 409s an existing name the same way).
        self.launch_counts: dict[str, int] = {}
        self.duplicate_applies: list[str] = []

    # -- verbs -------------------------------------------------------------

    def apply(self, manifest: dict) -> None:
        kind = manifest.get("kind")
        if kind == "Service":
            name = manifest["metadata"]["name"]
            with self._lock:
                if name not in self.service_ports:
                    # distinct loopback port per service: concurrent
                    # distributed runs must not share one coordinator port
                    # (real clusters separate by pod IP; loopback can't)
                    import socket

                    s = socket.socket()
                    s.bind(("127.0.0.1", 0))
                    self.service_ports[name] = s.getsockname()[1]
                    s.close()
                self.services[name] = manifest
            return
        if kind != "Pod":
            raise ValueError(f"FakeCluster cannot apply kind {kind!r}")
        name = manifest["metadata"]["name"]
        run_label = ((manifest.get("metadata") or {}).get("labels")
                     or {}).get("app.polyaxon.com/run")
        with self._lock:
            if name in self.pods:
                self.duplicate_applies.append(name)
                raise ValueError(f"pod {name!r} already exists")
            pod = _FakePod(manifest=manifest)
            self.pods[name] = pod
            if run_label:
                self.launch_counts[run_label] = \
                    self.launch_counts.get(run_label, 0) + 1
        self._launch(pod)

    def delete(self, kind: str, name: str) -> None:
        if kind == "Service":
            with self._lock:
                self.services.pop(name, None)
            return
        with self._lock:
            pod = self.pods.pop(name, None)
        if pod and pod.proc and pod.proc.poll() is None:
            pod.proc.terminate()
            try:
                pod.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pod.proc.kill()

    def delete_selected(self, label_selector: dict[str, str]) -> None:
        with self._lock:
            pods = [p.name for p in self.pods.values()
                    if _match_labels(p.manifest, label_selector)]
            svcs = [name for name, m in self.services.items()
                    if _match_labels(m, label_selector)]
        for n in pods:
            self.delete("Pod", n)
        for n in svcs:
            self.delete("Service", n)

    def pod_statuses(self, label_selector: dict[str, str]) -> list[PodStatus]:
        with self._lock:
            pods = [p for p in self.pods.values()
                    if _match_labels(p.manifest, label_selector)]
        return [p.phase() for p in pods]

    def pod_logs(self, name: str) -> str:
        with self._lock:
            pod = self.pods.get(name)
        if pod is None or not pod.log_path or not os.path.exists(pod.log_path):
            return ""
        with open(pod.log_path, encoding="utf-8", errors="replace") as f:
            return f.read()

    def run_pods(self, label_key: str = "app.polyaxon.com/run",
                 ) -> dict[str, list[PodStatus]]:
        out: dict[str, list[PodStatus]] = {}
        with self._lock:
            pods = list(self.pods.values())
        for p in pods:
            labels = (p.manifest.get("metadata") or {}).get("labels") or {}
            uuid = labels.get(label_key)
            if uuid:
                out.setdefault(uuid, []).append(p.phase())
        return out

    def shutdown(self) -> None:
        """Kill every pod process (test teardown / agent stop)."""
        with self._lock:
            names = list(self.pods)
        for n in names:
            self.delete("Pod", n)

    # -- pod launch --------------------------------------------------------

    def _rewrite_dns(self, value: str) -> str:
        """Rewrite <pod>.<registered-service>[:port] references to loopback,
        remapping the port to the service's allocated local port."""
        for svc, port in self.service_ports.items():
            value = re.sub(
                rf"[A-Za-z0-9.-]+\.{re.escape(svc)}:\d+", f"127.0.0.1:{port}", value,
            )
            value = re.sub(rf"[A-Za-z0-9.-]+\.{re.escape(svc)}", "127.0.0.1", value)
        return value

    def _launch(self, pod: _FakePod) -> None:
        import sys

        from ..runtime.local import _with_pythonpath, pod_base_env

        spec = pod.manifest.get("spec") or {}
        containers = spec.get("containers") or []
        c = containers[0] if containers else {}
        argv = list(c.get("command") or []) + list(c.get("args") or [])
        env = pod_base_env()
        for e in c.get("env") or []:
            if e.get("value") is not None:
                env[e["name"]] = self._rewrite_dns(str(e["value"]))
        # source tree importable inside "pods" (no image build locally)
        env = _with_pythonpath(env)
        self.launched_env[pod.name] = {
            e["name"]: env[e["name"]] for e in (c.get("env") or []) if e.get("value") is not None
        }
        if not argv:
            # no command: a real kubelet would run the image entrypoint; the
            # fake cluster has no images, so an argv-less pod just "succeeds"
            pod.forced_phase = PodPhase.SUCCEEDED
            return
        if argv[0] in ("python", "python3"):
            # the fake kubelet's image-entrypoint resolution: manifests say
            # "python" (correct inside a container image); locally that must
            # be this interpreter
            argv[0] = sys.executable
        cwd = c.get("workingDir") or self.workdir
        os.makedirs(cwd, exist_ok=True)
        pod.log_path = os.path.join(self.workdir, f"{pod.name}.log")
        log_file = open(pod.log_path, "w", encoding="utf-8")
        # fake kubelet: initContainers run sequentially before main, a
        # non-zero exit fails the pod (real kubelet semantics). They run
        # synchronously here — init steps are file/artifact fetches; a
        # pathological clone would stall this tick, which the test double
        # accepts for the determinism it buys.
        for ic in spec.get("initContainers") or []:
            argv_i = list(ic.get("command") or []) + list(ic.get("args") or [])
            if not argv_i:
                continue
            if argv_i[0] in ("python", "python3"):
                argv_i[0] = sys.executable
            env_i = pod_base_env()
            for e in ic.get("env") or []:
                if e.get("value") is not None:
                    env_i[e["name"]] = self._rewrite_dns(str(e["value"]))
            env_i = _with_pythonpath(env_i)
            icwd = ic.get("workingDir") or self.workdir
            os.makedirs(icwd, exist_ok=True)
            try:
                proc = subprocess.run(
                    argv_i, env=env_i, cwd=icwd, stdout=log_file,
                    stderr=subprocess.STDOUT, timeout=600,
                )
            except (OSError, subprocess.TimeoutExpired) as e:
                log_file.write(f"[init:{ic.get('name')}] launch failed: {e}\n")
                log_file.close()
                pod.forced_phase = PodPhase.FAILED
                return
            if proc.returncode != 0:
                log_file.write(
                    f"[init:{ic.get('name')}] exit code {proc.returncode}\n")
                log_file.close()
                pod.forced_phase = PodPhase.FAILED
                return
        try:
            pod.proc = subprocess.Popen(
                argv, env=env, cwd=cwd,
                stdout=log_file, stderr=subprocess.STDOUT,
            )
            # the child owns its copy of the fd now; closing ours avoids
            # leaking one handle per pod on long-lived agents
            log_file.close()
        except OSError as e:
            pod.forced_phase = PodPhase.FAILED
            log_file.write(f"[fake-cluster] launch failed: {e}\n")
            log_file.close()
