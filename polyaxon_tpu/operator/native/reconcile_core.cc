// Reconcile decision kernel — the native core of the L3 operator.
//
// Upstream's operator is its one native-compiled component (a Go
// controller-runtime reconciler on the Operation CRD — SURVEY.md §2
// "Operator" row). Per SURVEY.md §7 hard part (d), the TPU-native port keeps
// the reconciler minimal and native while rendering/IO stay in Python: this
// translation unit is a PURE function from observed cluster state to a
// decision, so it is trivially testable and shares none of Python's GIL or
// allocation behavior on the hot reconcile path.
//
// Slice semantics (SURVEY.md §5 "failure detection"): TPU jobs restart
// all-or-nothing — one failed host pod invalidates the whole ICI slice, so
// the only retry action is "delete every pod and re-apply".

#include <cstdint>

extern "C" {

enum plx_action : int32_t {
  PLX_WAIT = 0,         // nothing to do this pass
  PLX_SET_RUNNING = 1,  // first pod entered Running -> operation is running
  PLX_RESTART = 2,      // slice-level retry: delete ALL pods, re-apply
  PLX_FAIL = 3,         // terminal failure: delete pods, patch status failed
  PLX_SUCCEED = 4,      // every pod succeeded: patch status succeeded
  PLX_GC = 5,           // TTL elapsed after finish: delete all resources
};

enum plx_reason : int32_t {
  PLX_R_NONE = 0,
  PLX_R_DEADLINE = 1,   // activeDeadlineSeconds exceeded
  PLX_R_POD_FAILED = 2, // >=1 pod failed, no retries left
  PLX_R_COMPLETED = 3,
  PLX_R_TTL = 4,
  PLX_R_BACKOFF = 5,    // restarting within backoff budget
};

struct plx_observed {
  int32_t pods_total;
  int32_t pending;
  int32_t running;
  int32_t succeeded;
  int32_t failed;
  int32_t retries_done;
  int32_t backoff_limit;
  int32_t is_finished;      // operation already reached a terminal status
  int32_t was_running;      // SET_RUNNING already emitted for this attempt
  double elapsed_s;         // since current attempt's apply
  double finished_for_s;    // since terminal status (0 when not finished)
  double active_deadline_s; // <=0 => no deadline
  double ttl_s;             // <0 => no TTL; 0 => immediate GC on finish
};

struct plx_decision {
  int32_t action;
  int32_t reason;
};

// Returns 0 on success, -1 on invalid input. Priority order matters and is
// part of the contract (mirrored by the Python fallback + parity test):
// GC > deadline > pod-failure > success > running > wait.
int32_t plx_reconcile(const plx_observed* obs, plx_decision* out) {
  if (obs == nullptr || out == nullptr) return -1;
  if (obs->pods_total < 0 || obs->pending < 0 || obs->running < 0 ||
      obs->succeeded < 0 || obs->failed < 0)
    return -1;
  out->action = PLX_WAIT;
  out->reason = PLX_R_NONE;

  if (obs->is_finished) {
    if (obs->ttl_s >= 0.0 && obs->finished_for_s >= obs->ttl_s) {
      out->action = PLX_GC;
      out->reason = PLX_R_TTL;
    }
    return 0;
  }

  if (obs->active_deadline_s > 0.0 && obs->elapsed_s > obs->active_deadline_s) {
    out->action = PLX_FAIL;
    out->reason = PLX_R_DEADLINE;
    return 0;
  }

  // A failed pod, or a slice whose pods vanished wholesale after it was
  // running (node GC, external delete), is slice loss either way; without
  // the vanished-pods arm the operation would WAIT forever on an empty
  // pod set.
  if (obs->failed > 0 || (obs->pods_total == 0 && obs->was_running)) {
    // all-or-nothing: even with partial success, the slice restarts whole
    if (obs->retries_done < obs->backoff_limit) {
      out->action = PLX_RESTART;
      out->reason = PLX_R_BACKOFF;
    } else {
      out->action = PLX_FAIL;
      out->reason = PLX_R_POD_FAILED;
    }
    return 0;
  }

  if (obs->pods_total > 0 && obs->succeeded == obs->pods_total) {
    out->action = PLX_SUCCEED;
    out->reason = PLX_R_COMPLETED;
    return 0;
  }

  if (obs->running > 0 && !obs->was_running) {
    out->action = PLX_SET_RUNNING;
    return 0;
  }

  return 0;
}

int32_t plx_abi_version() { return 1; }

}  // extern "C"
