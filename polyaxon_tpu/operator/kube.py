"""Real-Kubernetes ``Cluster`` backend over the K8s REST API.

The role upstream's Go operator filled through controller-runtime
(SURVEY.md §2 "Operator" row): apply/delete pods + services, read pod
phases, stream logs. Stdlib-only HTTP (no kubernetes client dependency —
the env bakes none): in-cluster service-account auth (token + CA from
``/var/run/secrets/kubernetes.io/serviceaccount``) or explicit host/token,
e.g. from a kubeconfig-derived env.

The reconciler stays the brain (polling reconcile passes, C++ decision
kernel); this class is only the verbs, so FakeCluster and KubeCluster are
interchangeable behind the same ``Cluster`` ABC — which is how the entire
operator layer stays testable without a kubelet (SURVEY.md §4).
"""

from __future__ import annotations

import json
import os
import ssl
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Optional

from .cluster import Cluster, PodPhase, PodStatus

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(RuntimeError):
    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(f"K8s API {status}: {message[:300]}")
        self.status = status
        # apiserver flow control: 429/503 carry Retry-After; the shared
        # RetryPolicy honors it over its computed backoff
        self.retry_after = retry_after


class KubeCluster(Cluster):
    """Cluster verbs against a real K8s API server.

    Args:
        host: API server base URL (default: in-cluster
            ``https://$KUBERNETES_SERVICE_HOST:$KUBERNETES_SERVICE_PORT``).
        token: bearer token (default: the mounted service-account token).
        namespace: target namespace (default: the service account's).
        ca_file: CA bundle; ``verify=False`` disables TLS verification
            (dev clusters).
    """

    def __init__(
        self,
        host: Optional[str] = None,
        token: Optional[str] = None,
        namespace: Optional[str] = None,
        ca_file: Optional[str] = None,
        verify: bool = True,
        timeout: float = 10.0,
        replace_timeout: float = 30.0,
        retry: Optional["RetryPolicy"] = None,
    ):
        from ..resilience.retry import DEFAULT_HTTP_RETRY

        # Transient-failure policy for every verb (VERDICT r5 Missing #3:
        # these paths had no retry at all). Safe across verbs: GET/DELETE
        # are idempotent, and a duplicated POST surfaces as the 409 that
        # apply() already resolves. Pass a policy with max_attempts=1 to
        # disable.
        self.retry = retry if retry is not None else DEFAULT_HTTP_RETRY
        if host is None:
            h = os.environ.get("KUBERNETES_SERVICE_HOST")
            p = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not h:
                raise ValueError(
                    "KubeCluster needs `host` or in-cluster env "
                    "(KUBERNETES_SERVICE_HOST)"
                )
            host = f"https://{h}:{p}"
        self.host = host.rstrip("/")
        if token is None:
            token_path = os.path.join(SA_DIR, "token")
            token = open(token_path, encoding="utf-8").read().strip() \
                if os.path.exists(token_path) else None
        self.token = token
        if namespace is None:
            ns_path = os.path.join(SA_DIR, "namespace")
            namespace = open(ns_path, encoding="utf-8").read().strip() \
                if os.path.exists(ns_path) else "default"
        self.namespace = namespace
        self.timeout = timeout
        self._replace_timeout = replace_timeout
        if ca_file is None and os.path.exists(os.path.join(SA_DIR, "ca.crt")):
            ca_file = os.path.join(SA_DIR, "ca.crt")
        if self.host.startswith("https"):
            self._ssl: Optional[ssl.SSLContext] = (
                ssl.create_default_context(cafile=ca_file) if verify
                else ssl._create_unverified_context()  # noqa: S323 — opt-in
            )
        else:
            self._ssl = None

    # -- HTTP plumbing -------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 raw: bool = False) -> Any:
        """One K8s API call, retried per ``self.retry`` on transient
        failures (5xx/429 — honoring Retry-After — plus socket timeouts
        and connection errors). Non-transient statuses (404/409/...)
        surface immediately, unchanged."""
        return self.retry.call(self._request_once, method, path, body, raw)

    def _request_once(self, method: str, path: str, body: Optional[dict] = None,
                      raw: bool = False) -> Any:
        url = f"{self.host}{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if body is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            from ..resilience.retry import parse_retry_after

            raise KubeApiError(e.code, e.read().decode(errors="replace"),
                               retry_after=parse_retry_after(e.headers)) from e
        if raw:
            return payload.decode(errors="replace")
        return json.loads(payload) if payload else {}

    def _resource_path(self, kind: str, name: str = "") -> str:
        plural = {"Pod": "pods", "Service": "services"}[kind]
        suffix = f"/{name}" if name else ""
        return f"/api/v1/namespaces/{self.namespace}/{plural}{suffix}"

    # -- Cluster verbs -------------------------------------------------------

    def apply(self, manifest: dict) -> None:
        kind = manifest.get("kind")
        if kind not in ("Pod", "Service"):
            raise ValueError(f"KubeCluster cannot apply kind {kind!r}")
        name = manifest["metadata"]["name"]
        try:
            self._request("POST", self._resource_path(kind), body=manifest)
            return
        except KubeApiError as e:
            if e.status != 409:
                raise
        # AlreadyExists. A Service re-apply is idempotent — keep the old
        # one. A Pod conflict means a prior attempt's pod (possibly still
        # Terminating: K8s DELETE returns before etcd removal): replace it,
        # or the reconciler's RESTART would silently adopt the dead pod and
        # burn its backoff budget without ever re-running.
        if kind != "Pod":
            return
        self.delete(kind, name)
        deadline = time.monotonic() + self._replace_timeout
        while True:
            try:
                self._request("POST", self._resource_path(kind), body=manifest)
                return
            except KubeApiError as e:
                if e.status != 409 or time.monotonic() > deadline:
                    raise
            time.sleep(0.5)

    def delete(self, kind: str, name: str) -> None:
        try:
            self._request(
                "DELETE", self._resource_path(kind, name),
                body={"gracePeriodSeconds": 0, "propagationPolicy": "Background"},
            )
        except KubeApiError as e:
            if e.status != 404:  # already gone
                raise

    def delete_selected(self, label_selector: dict[str, str]) -> None:
        sel = self._selector(label_selector)
        # pods support collection delete; services must go one by one
        try:
            self._request(
                "DELETE", self._resource_path("Pod") + "?labelSelector=" + sel,
                body={"gracePeriodSeconds": 0, "propagationPolicy": "Background"},
            )
        except KubeApiError as e:
            if e.status != 404:
                raise
        svc_list = self._request(
            "GET", self._resource_path("Service") + "?labelSelector=" + sel)
        for item in svc_list.get("items", []):
            self.delete("Service", item["metadata"]["name"])

    @staticmethod
    def _selector(label_selector: dict) -> str:
        """Equality selectors; a None value means key-existence (the watch
        uses this to follow only this framework's pods)."""
        return urllib.parse.quote(",".join(
            k if v is None else f"{k}={v}"
            for k, v in sorted(label_selector.items())))

    def pod_statuses(self, label_selector: dict[str, str]) -> list[PodStatus]:
        path = self._resource_path("Pod") + "?labelSelector=" + \
            self._selector(label_selector)
        out = []
        for item in self._request("GET", path).get("items", []):
            out.append(self._to_status(item))
        return out

    def pod_logs(self, name: str) -> str:
        try:
            return self._request(
                "GET", self._resource_path("Pod", name) + "/log", raw=True)
        except KubeApiError as e:
            if e.status == 404:
                return ""
            raise

    def run_pods(self, label_key: str = "app.polyaxon.com/run",
                 ) -> dict[str, list[PodStatus]]:
        """ONE key-existence listing grouped by run label (the agent's
        cold-start resync verb — O(1) API calls however many runs are
        in flight)."""
        path = (self._resource_path("Pod") + "?labelSelector="
                + self._selector({label_key: None}))
        out: dict[str, list[PodStatus]] = {}
        for item in self._request("GET", path).get("items", []):
            uuid = ((item.get("metadata") or {}).get("labels")
                    or {}).get(label_key)
            if uuid:
                out.setdefault(uuid, []).append(self._to_status(item))
        return out

    def service_host(self, name: str) -> str:
        """Service DNS name — resolvable from any pod in the cluster, so
        the agent (which runs in-cluster) can proxy ``port-forward``
        traffic to it."""
        return f"{name}.{self.namespace}.svc"

    # -- watch ---------------------------------------------------------------

    def watch_pods(self, label_selector: dict[str, str], on_event,
                   stop_event=None) -> None:
        """Stream pod change events (upstream's operator was watch-driven,
        not poll-driven). Blocks until ``stop_event`` is set. ``on_event(
        type, pod_status)`` fires per event — typically a closure that pokes
        the reconciler instead of waiting for its next poll tick.

        Resumable (the controller-runtime contract, VERDICT r3 missing #4):
        the stream position — each event object's ``resourceVersion`` — is
        tracked, reconnects resume from it, and bookmarks advance it, so
        events between streams are not lost. On 410 Gone (history
        compacted: HTTP status or ERROR event) the watch re-LISTs, emits
        each current pod as a ``SYNC`` event (level-based consumers treat
        it like MODIFIED) and resumes from the list's resourceVersion.
        """
        import sys
        import threading

        stop_event = stop_event or threading.Event()
        sel = self._selector(label_selector)
        rv: Optional[str] = None
        backoff = 1.0
        while not stop_event.is_set():
            if rv is None:
                # (re-)list: sync current state, pick up the stream position
                try:
                    listing = self._request(
                        "GET",
                        self._resource_path("Pod") + "?labelSelector=" + sel)
                except (KubeApiError, urllib.error.URLError,
                        TimeoutError, OSError) as e:
                    print(f"[kube-watch] list failed {e!r}; retrying in "
                          f"{backoff:.0f}s", file=sys.stderr)
                    stop_event.wait(backoff)
                    backoff = min(backoff * 2, 60.0)
                    continue
                rv = (listing.get("metadata") or {}).get("resourceVersion")
                for item in listing.get("items", []):
                    on_event("SYNC", self._to_status(item))
            path = (self._resource_path("Pod")
                    + "?watch=true&allowWatchBookmarks=true&labelSelector="
                    + sel + (f"&resourceVersion={rv}" if rv else ""))
            try:
                req = urllib.request.Request(self.host + path, method="GET")
                if self.token:
                    req.add_header("Authorization", f"Bearer {self.token}")
                with urllib.request.urlopen(
                        req, timeout=30, context=self._ssl) as resp:
                    backoff = 1.0  # stream established
                    for line in resp:
                        if stop_event.is_set():
                            return
                        try:
                            event = json.loads(line)
                        except ValueError:
                            continue
                        typ = event.get("type", "")
                        obj = event.get("object") or {}
                        if typ == "ERROR":
                            if obj.get("code") == 410:
                                rv = None  # history gone: re-list
                            break  # reconnect either way
                        new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                        if new_rv:
                            rv = new_rv
                        if typ == "BOOKMARK":
                            continue  # position-only event
                        if obj.get("kind") == "Pod":
                            on_event(typ, self._to_status(obj))
            except urllib.error.HTTPError as e:
                if e.code == 410:
                    rv = None  # re-list immediately, no backoff
                    continue
                print(f"[kube-watch] {e!r}; retrying in {backoff:.0f}s",
                      file=sys.stderr)
                stop_event.wait(backoff)
                backoff = min(backoff * 2, 60.0)
                continue
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                # a permanent 401/403 (bad token, role missing the watch
                # verb) must be visible, not a silent 1 Hz retry loop
                print(f"[kube-watch] {e!r}; retrying in {backoff:.0f}s",
                      file=sys.stderr)
                stop_event.wait(backoff)
                backoff = min(backoff * 2, 60.0)
                continue
            stop_event.wait(0.05 if rv is None else 0.2)  # then reconnect

    # -- translation ---------------------------------------------------------

    @staticmethod
    def _to_status(pod: dict) -> PodStatus:
        name = pod["metadata"]["name"]
        status = pod.get("status") or {}
        phase_raw = status.get("phase", "Pending")
        phase = {
            "Pending": PodPhase.PENDING,
            "Running": PodPhase.RUNNING,
            "Succeeded": PodPhase.SUCCEEDED,
            "Failed": PodPhase.FAILED,
            # Unknown (node gone) counts as failed: slice-level restart
            # semantics want all-or-nothing anyway
            "Unknown": PodPhase.FAILED,
        }.get(phase_raw, PodPhase.PENDING)
        exit_code = None
        message = status.get("message")
        for cs in status.get("containerStatuses") or []:
            term = (cs.get("state") or {}).get("terminated")
            if term:
                exit_code = term.get("exitCode")
                message = message or term.get("reason")
        return PodStatus(
            name, phase, exit_code=exit_code, message=message,
            terminating=bool(pod["metadata"].get("deletionTimestamp")),
        )
