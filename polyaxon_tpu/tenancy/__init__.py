"""Multi-tenant scheduling (ISSUE 15): tenant identity, chip quotas,
priority classes, weighted fair-share ordering, and API rate limiting.

The control plane's identity seams already existed — ``created_by`` is
derived from the stable token id at run creation, and PR-14's tokens are
project-scoped capabilities. This package turns those seams into a real
tenancy layer, the Borg-style subsystem every production training stack
grows:

- **tenant** — the accounting unit. Stamped on every run at creation
  (explicit, or derived from ``created_by`` via :func:`tenant_of`); runs
  with no identity land in :data:`DEFAULT_TENANT`.
- **quota** — per-tenant chip budget (``quotas`` store table, served by
  ``PUT/GET /api/v1/quotas/{tenant}``). Over-quota work is *parked*
  (``queued`` with an ``OverQuota`` condition), never dropped.
- **priority class** — ``high | normal | preemptible`` on the
  polyaxonfile operation, compile-time validated. Higher classes may
  preempt strictly-lower-class *training* runs (never services) through
  the existing graceful-stop → checkpoint → ``queued(Preempted)`` path.
- **weighted fair share** — the agent's per-shard FIFO wait queues
  become a DRF-style walk ordered by (priority class, tenant
  usage/quota ratio, created_at): FIFO is preserved within one
  tenant+class, and a single tenant degrades to plain FIFO exactly.
- **rate limiting** — per-tenant token buckets on the API's write
  endpoints (:class:`TenantRateLimiter`), answering 429 + Retry-After
  in the PR-12 serve idiom.

Everything here is pure policy/state: no store or scheduler imports, so
the api/ and scheduler/ layers can both depend on it without cycles.
docs/SCHEDULING.md is the operator-facing contract.
"""

from .fairshare import (  # noqa: F401
    DEFAULT_TENANT,
    NORMAL_RANK,
    PRIORITY_CLASSES,
    jain_index,
    priority_rank,
    run_priority,
    select_victims,
    tenant_of,
)
from .ratelimit import TenantRateLimiter, TokenBucket  # noqa: F401

__all__ = [
    "DEFAULT_TENANT",
    "NORMAL_RANK",
    "PRIORITY_CLASSES",
    "TenantRateLimiter",
    "TokenBucket",
    "jain_index",
    "priority_rank",
    "run_priority",
    "select_victims",
    "tenant_of",
]
