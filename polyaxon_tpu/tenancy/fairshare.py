"""Fair-share policy primitives: tenant identity, priority classes, DRF
ordering keys, victim selection, and the Jain fairness index the benches
assert convergence with.

Pure functions over plain dicts — the agent's scheduling walk and the
soak/bench harnesses share these so "what the scheduler does" and "what
the test asserts" cannot drift apart.
"""

from __future__ import annotations

from typing import Optional

#: runs with no identity (auth off, direct store writers) account here
DEFAULT_TENANT = "default"

#: class name -> rank; LOWER rank wins the walk and may preempt strictly
#: higher ranks. "normal" is the default for specs that say nothing.
PRIORITY_CLASSES = {"high": 0, "normal": 1, "preemptible": 2}
NORMAL_RANK = PRIORITY_CLASSES["normal"]


def priority_rank(priority: Optional[str]) -> int:
    """Rank for a priority-class name. Unknown/absent values rank as
    ``normal`` — the compiler validates the polyaxonfile field, so an
    unknown string here can only come from a raw store write, and the
    scheduler must not KeyError over it."""
    return PRIORITY_CLASSES.get(priority or "normal", NORMAL_RANK)


def tenant_of(created_by: Optional[str]) -> str:
    """Tenant derived from a run's ``created_by`` identity.

    ``created_by`` is ``label#id`` for labelled tokens and ``token-<id>``
    for unlabelled ones (ADVICE r5: the stable token id, never the
    user-chosen label alone). The tenant is the LABEL half — two tokens
    labelled "ci" are the same tenant for accounting even though they are
    distinct identities — and the full identity for unlabelled tokens.
    ``admin`` and anonymous callers account to :data:`DEFAULT_TENANT`."""
    if not created_by or created_by == "admin":
        return DEFAULT_TENANT
    label, sep, _ = created_by.partition("#")
    return label if sep and label else created_by


def run_priority(run: dict) -> str:
    """The priority class of a run row (compiled spec wins — it is the
    validated one — falling back to the raw spec for pre-compile rows)."""
    for key in ("compiled", "spec"):
        doc = run.get(key)
        if isinstance(doc, dict) and doc.get("priority"):
            return str(doc["priority"])
    return "normal"


def drf_key(rank: int, usage: float, quota: Optional[int],
            seq: int) -> tuple:
    """Ordering key for one tenant+class queue head: (priority rank,
    dominant-share ratio, admission sequence). Tenants with no quota
    (tenancy off, or an unlimited tenant) compare at ratio 0 — among
    themselves that reduces to (rank, seq): priority-FIFO, and with one
    tenant and one class to plain FIFO, the r7 walk exactly."""
    ratio = (usage / quota) if quota else 0.0
    return (rank, ratio, seq)


def select_victims(running: list[dict], chips: dict, rank: int,
                   needed: int) -> Optional[list[dict]]:
    """Pick preemption victims for a blocked run of class ``rank``.

    ``running``: candidate run rows (the caller pre-filters to runs it
    owns and drives); ``chips``: {uuid: reserved chips}. Victims must be
    strictly lower class (rank > ``rank``), must be *compute* — service
    runs are never preempted, only training — and are taken newest-first
    (by created_at), so the work lost to a preemption is the work that
    has made the least progress. Returns the victim rows once their
    freed chips cover ``needed``, or None when even preempting every
    eligible run would not fit the candidate (preempting anyway would
    kill work without unblocking anything)."""
    eligible = []
    for run in running:
        if run.get("kind") == "service":
            continue
        if priority_rank(run_priority(run)) <= rank:
            continue
        eligible.append(run)
    eligible.sort(key=lambda r: (r.get("created_at") or "", r["uuid"]),
                  reverse=True)
    victims, freed = [], 0
    for run in eligible:
        victims.append(run)
        freed += max(int(chips.get(run["uuid"], 0)), 0)
        if freed >= needed:
            return victims
    return None


def jain_index(values: list[float]) -> float:
    """Jain's fairness index over per-tenant normalized shares:
    ``(sum x)^2 / (n * sum x^2)``. 1.0 = perfectly quota-proportional;
    1/n = one tenant holds everything. The soak/bench acceptance bound
    is computed over mean steady-window shares divided by quota."""
    xs = [float(v) for v in values]
    if not xs:
        return 1.0
    sq = sum(x * x for x in xs)
    if sq == 0.0:
        return 1.0
    return (sum(xs) ** 2) / (len(xs) * sq)
