"""Per-tenant token-bucket rate limiting for the API's write endpoints
(ISSUE 15, PR-12 serve idiom: shed with 429 + Retry-After, never queue
unbounded work).

Buckets run on ``time.monotonic()`` — refill arithmetic is a duration on
one machine, and an NTP step must not mint (or confiscate) a burst of
tokens. The R4 clock rule covers this module (``tenancy/`` is in its
scope); the corpus pair ``analysis_corpus/tenancy/r15_*`` pins the
bug class.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second refill, ``burst``
    capacity. ``acquire(n)`` is non-blocking — it either spends the
    tokens or answers how long until they exist."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate!r}")
        self.rate = float(rate)
        self.burst = float(burst) if burst else max(self.rate * 2.0, 1.0)
        self._tokens = self.burst
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        elapsed = max(now - self._stamp, 0.0)
        self._stamp = now
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate)

    def acquire(self, n: float = 1.0) -> tuple[bool, float]:
        """Try to spend ``n`` tokens. Returns ``(True, 0.0)`` on success
        or ``(False, retry_after_seconds)`` — the time until ``n`` tokens
        will have refilled, the Retry-After the API answers with."""
        now = time.monotonic()
        with self._lock:
            self._refill(now)
            if self._tokens >= n:
                self._tokens -= n
                return True, 0.0
            return False, (n - self._tokens) / self.rate


class TenantRateLimiter:
    """One :class:`TokenBucket` per tenant, bounded LRU so an identity
    churn (many one-shot tokens) cannot grow the map without bound. All
    tenants share one (rate, burst) policy — quotas differentiate
    *capacity*; the rate limit only protects the API write path."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 max_tenants: int = 1024):
        self.rate = float(rate)
        self.burst = burst
        self.max_tenants = int(max_tenants)
        self._buckets: "collections.OrderedDict[str, TokenBucket]" = (
            collections.OrderedDict())
        self._lock = threading.Lock()

    def _bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = TokenBucket(self.rate, self.burst)
                self._buckets[tenant] = b
                while len(self._buckets) > self.max_tenants:
                    # evict the least-recently-used bucket; a revived
                    # tenant just starts a fresh (full) bucket
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(tenant)
            return b

    def acquire(self, tenant: str, n: float = 1.0) -> tuple[bool, float]:
        return self._bucket(tenant).acquire(n)
