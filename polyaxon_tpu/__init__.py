"""polyaxon_tpu — a TPU-native ML orchestration + training framework.

Re-implements the capabilities of Polyaxon (reference: sboorlagadda/polyaxon;
mount was empty at survey time — see SURVEY.md status banner) as a brand-new
TPU-first system:

- ``schemas``:       polyflow-equivalent spec objects (Component/Operation/run
                     kinds/matrix kinds), including the new TPU-native run
                     kinds ``tpujob``/``jaxjob``.
- ``polyaxonfile``:  YAML spec parsing, validation, ``--set`` overrides, presets.
- ``compiler``:      Operation + Component -> CompiledOperation -> executable
                     payload (TPU slice topology, jax.distributed env).
- ``api``:           aiohttp REST API + streams service over SQLite.
- ``scheduler``:     queue + agent + topology-aware ICI sub-slice bin-packing.
- ``operator``:      reconciler (C++ core with Python fake-cluster backend).
- ``runtime``:       init/sidecar equivalents + local subprocess executor.
- ``tracking``:      traceml-equivalent event tracking/lineage.
- ``hypertune``:     grid/random/mapping/Hyperband/Bayesian search.
- ``models``/``ops``/``parallel``/``train``: the JAX/pallas/pjit training
  runtime the reference never owned (Llama, ViT, ResNet, BERT, GPT-2;
  flash/ring attention; DP/FSDP/TP/PP/SP/EP over a device mesh).
"""

__version__ = "0.1.0"
