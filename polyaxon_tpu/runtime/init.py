"""Init steps — the init-container equivalents (SURVEY.md §2 "Init
container": fetch code/artifacts/files into the run's context before the
main process starts). Locally these run in-process before the subprocess."""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Any


class InitError(RuntimeError):
    pass


def run_init_step(step: dict[str, Any], run_dir: str) -> None:
    if step.get("git"):
        _init_git(step["git"], run_dir)
    elif step.get("file"):
        _init_file(step["file"], run_dir)
    elif step.get("dockerfile"):
        _init_file({"name": "Dockerfile", **step["dockerfile"]}, run_dir)
    elif step.get("paths") or step.get("artifacts"):
        _init_paths(step, run_dir)
    elif step.get("connection") or step.get("path"):
        _init_connection_path(step, run_dir)
    else:
        raise InitError(f"Unsupported init step: {sorted(k for k, v in step.items() if v)}")


def _init_git(spec: dict, run_dir: str) -> None:
    url = spec.get("url")
    if not url:
        raise InitError("git init step needs 'url'")
    dest = os.path.join(run_dir, "code")
    if os.path.isdir(os.path.join(dest, ".git")):
        # already cloned: a retry, or another host pod of a multi-host job
        # sharing one run dir (FakeCluster serializes init launches; real
        # kubelets give each pod its own emptyDir). Never re-clone — the
        # first pod's main container may already be running from dest.
        return
    # clone beside dest, then merge in: dest may already hold earlier
    # file/dockerfile init-step outputs that must survive
    tmp = dest + ".cloning"
    shutil.rmtree(tmp, ignore_errors=True)
    args = ["git", "clone", "--depth", "1"]
    if spec.get("revision"):
        args += ["--branch", spec["revision"]]
    args += list(spec.get("flags") or []) + [url, tmp]
    proc = subprocess.run(args, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        shutil.rmtree(tmp, ignore_errors=True)
        raise InitError(f"git clone failed: {proc.stderr[-500:]}")
    # Fold dest's earlier init-step outputs into the temp clone (clone
    # content wins on collision), then swap tmp into place. Each rename is
    # atomic, so an interruption at any point leaves either the old dest
    # (no .git — the retry re-clones) or the complete new checkout; the
    # .git marker can never latch onto a partial merge. Symlinks copy as
    # links — repos carry relative/broken links routinely.
    try:
        if os.path.isdir(dest):
            _merge_missing(dest, tmp)
        old = dest + ".old"
        shutil.rmtree(old, ignore_errors=True)
        if os.path.isdir(dest):
            os.rename(dest, old)
        os.rename(tmp, dest)
        shutil.rmtree(old, ignore_errors=True)
    except (OSError, shutil.Error) as e:
        shutil.rmtree(tmp, ignore_errors=True)
        raise InitError(f"git checkout merge failed: {e}") from e


def _merge_missing(src_dir: str, dst_dir: str) -> None:
    """Recursively copy entries of src_dir that dst_dir lacks (existing
    dst entries win); symlinks are recreated, never dereferenced."""
    for name in os.listdir(src_dir):
        s, d = os.path.join(src_dir, name), os.path.join(dst_dir, name)
        if os.path.islink(s):
            if not os.path.lexists(d):
                os.symlink(os.readlink(s), d)
        elif os.path.isdir(s):
            if os.path.lexists(d) and not os.path.isdir(d):
                continue  # dst's file wins over src's directory
            os.makedirs(d, exist_ok=True)
            _merge_missing(s, d)
        elif not os.path.lexists(d):
            shutil.copy2(s, d, follow_symlinks=False)


def _init_file(spec: dict, run_dir: str) -> None:
    content = spec.get("content", "")
    name = spec.get("filename") or spec.get("name") or "file"
    dest_dir = os.path.join(run_dir, "code")
    os.makedirs(dest_dir, exist_ok=True)
    with open(os.path.join(dest_dir, name), "w", encoding="utf-8") as f:
        f.write(content)
    if spec.get("chmod"):
        os.chmod(os.path.join(dest_dir, name), int(str(spec["chmod"]), 8))


def _init_paths(step: dict, run_dir: str) -> None:
    """Copy local paths (or artifact-store paths once fs connections are
    configured) into the context."""
    paths = step.get("paths") or (step.get("artifacts") or {}).get("files") or []
    dest_dir = os.path.join(run_dir, "artifacts_in")
    os.makedirs(dest_dir, exist_ok=True)
    for p in paths:
        src, dst = (p if isinstance(p, (list, tuple)) else (p, os.path.basename(str(p))))
        dst_full = os.path.join(dest_dir, dst)
        if os.path.isdir(src):
            shutil.copytree(src, dst_full, dirs_exist_ok=True)
        elif os.path.isfile(src):
            os.makedirs(os.path.dirname(dst_full) or dest_dir, exist_ok=True)
            shutil.copy2(src, dst_full)
        else:
            raise InitError(f"init path not found: {src}")


def _init_connection_path(step: dict, run_dir: str) -> None:
    """Fetch from an fsspec-backed connection path (gs://, s3://, local)."""
    from ..fs import download

    path = step.get("path")
    if not path:
        raise InitError("connection init step needs 'path'")
    dest = os.path.join(run_dir, "artifacts_in", os.path.basename(path.rstrip("/")))
    download(path, dest)


def main() -> None:
    """Init-container entrypoint (``python -m polyaxon_tpu.runtime.init``):
    the converter renders one pod initContainer per init step carrying the
    step spec in ``PLX_INIT_STEP``; a real kubelet (or the FakeCluster's
    fake one) runs them sequentially before the main container, the same
    contract upstream's init containers had (SURVEY.md §2 "Init
    container")."""
    import json
    import sys

    step = json.loads(os.environ["PLX_INIT_STEP"])
    run_dir = os.environ["PLX_ARTIFACTS_PATH"]
    try:
        run_init_step(step, run_dir)
    except InitError as e:
        print(f"[init] failed: {e}", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
