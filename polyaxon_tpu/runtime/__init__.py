"""Run-pod runtime equivalents: init steps, local subprocess executor,
sidecar sync, built-in trainer entry (SURVEY.md §2 init/sidecar rows)."""

from .init import InitError, run_init_step
from .local import LocalExecution, LocalExecutor
