"""Built-in training entry (`runtime:` section of tpujob/jaxjob specs).

Runs the framework's own Trainer for a named model from the zoo — the
workload path of the north star (`polyaxon run -f llama7b.yaml` trains with
our runtime, no user container needed). Reads its spec from
``PLX_BUILTIN_SPEC`` (JSON) and attaches tracking via the standard PLX_* env.

Spec keys:
    model: registry name (e.g. "llama2-7b", "llama-tiny", "vit-b16", ...)
    steps, batch_size, seq_len, learning_rate, warmup_steps, schedule,
    optimizer, remat, parallelism {data,fsdp,model,context,expert,stage},
    num_slices (multislice mesh: slice-major device order, data/fsdp over
    DCN — injected from the tpujob topology by the compiler),
    partition_rules ([[regex, spec], ...] — override/extend the built-in
    partition rule sets; compile-time validated; docs/PARTITIONING.md),
    import ({path, layout: auto|flat|hf-llama, dtype} — foreign-checkpoint
    ingest through the rule engine, straight into sharded buffers),
    lora ({rank, alpha, target} — freeze the base, train adapters),
    pp_microbatches / pp_remat_ticks (pipeline schedule: microbatch count,
    1F1B-style O(stages) activation stash),
    data {kind, path, ...}, checkpoint {save_interval_steps, max_to_keep},
    platform ("cpu" forces CPU — tests), num_cpu_devices,
    mu_dtype / nu_dtype / grad_dtype (e.g. "bfloat16" — HBM savers),
    loss_chunk_tokens (blockwise-CE chunk),
    profile (true or {steps: N}: capture a jax.profiler trace of N steps
    after warmup into outputs/profile — browsable via the artifacts API,
    loadable in XProf; SURVEY.md §5 tracing),
    resources (default true: background host/TPU telemetry every 10s into
    the run's events — host_cpu_percent, host_mem_*, tpu_hbm_*; false
    disables, {interval: N} tunes; charted in the dashboard's Resources
    section)
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import replace
from typing import Any


def run_builtin(spec: dict[str, Any]) -> dict[str, Any]:
    platform = spec.get("platform")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
        if spec.get("num_cpu_devices"):
            try:
                jax.config.update("jax_num_cpu_devices", int(spec["num_cpu_devices"]))
            except AttributeError:
                # jax < 0.5: the underlying XLA flag is read at first
                # backend init, still ahead of us in a fresh pod process
                flags = os.environ.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    os.environ["XLA_FLAGS"] = (
                        flags + " --xla_force_host_platform_device_count="
                        f"{int(spec['num_cpu_devices'])}").strip()

    from .. import tracking
    from ..models import REGISTRY
    from ..parallel import initialize as dist_init
    from ..train import (
        CheckpointConfig, DataConfig, OptimizerConfig, Trainer, TrainerConfig,
        make_batches,
    )

    dist_init()  # joins jax.distributed when PLX_COORDINATOR_* present

    from ..train.tasks import task_for

    name = spec.get("model", "llama-tiny")
    if name not in REGISTRY:
        raise SystemExit(f"Unknown model {name!r}; available: {sorted(REGISTRY)}")
    family, mcfg = REGISTRY[name]

    if family in ("lm", "mlm"):
        overrides = {}
        if spec.get("remat"):
            overrides["remat"] = spec["remat"]
        if spec.get("loss_chunk_tokens") is not None:
            overrides["loss_chunk_tokens"] = int(spec["loss_chunk_tokens"])
        if spec.get("moe_dispatch"):
            # "capacity" (default) | "a2a" (explicit all-to-all over the
            # expert axis) | "dense" (parity oracle)
            overrides["moe_dispatch"] = spec["moe_dispatch"]
        if spec.get("moe_cap_block") is not None:
            # stream the capacity dispatch per cap-chunk (models/
            # transformer.py _moe_capacity_streamed); 0 = one-shot
            overrides["moe_cap_block"] = int(spec["moe_cap_block"])
        for knob in ("attn_block_q", "attn_block_k",
                     "attn_block_q_bwd", "attn_block_k_bwd"):
            # flash kernel block shapes (fwd + independently-retuned bwd) —
            # the measured single-chip recipes pin these (BASELINE.md)
            if spec.get(knob) is not None:
                overrides[knob] = int(spec[knob])
        if spec.get("pp_microbatches") is not None:
            overrides["pp_microbatches"] = int(spec["pp_microbatches"])
        if spec.get("pp_remat_ticks") is not None:
            # 1F1B-style O(stages) activation stash (parallel/pipeline.py)
            overrides["pp_remat_ticks"] = bool(spec["pp_remat_ticks"])
        if spec.get("pp_gate"):
            # "auto" | "full" | "inner" | "none" — "none" is the documented
            # choice for CPU-mesh pipeline runs (bench_artifacts/README.md)
            overrides["pp_gate"] = spec["pp_gate"]
        seq_len = int(spec.get("seq_len", min(2048, mcfg.max_seq)))
        if seq_len > mcfg.max_seq:
            overrides["max_seq"] = seq_len
        if overrides:
            mcfg = replace(mcfg, **overrides)
        task = task_for(family, mcfg)
        vocab_size = mcfg.vocab_size
        image_size = num_classes = None
    elif family == "vit":
        seq_len = mcfg.num_patches + 1
        task = task_for(family, mcfg)
        vocab_size = None
        image_size, num_classes = mcfg.image_size, mcfg.num_classes
    elif family == "resnet":
        image_size = int(spec.get("image_size", 32 if mcfg.small_inputs else 224))
        seq_len = 1
        task = task_for(family, mcfg, image_size=image_size)
        vocab_size = None
        num_classes = mcfg.num_classes
    else:
        raise SystemExit(f"no builtin task for model family {family!r}")

    steps = int(spec.get("steps", 100))
    batch_size = int(spec.get("batch_size", 8))
    import jax

    # In multi-process runs every process executes the same SPMD program but
    # only process 0 owns tracking/outputs (they share one artifacts dir).
    is_primary = jax.process_index() == 0
    run = tracking.get_run() if is_primary else None
    artifacts_dir = run.run_dir if run else os.environ.get("PLX_ARTIFACTS_PATH", os.getcwd())
    # a leftover progress.json describes a DEAD attempt: drop it before
    # anything can mistake its frozen step for this attempt's progress
    # (the agent also drops it on the retrying edge — this covers
    # restart paths that never pass through this agent)
    try:
        os.unlink(os.path.join(artifacts_dir, "progress.json"))
    except OSError:
        pass

    ckpt_spec = spec.get("checkpoint") or {}
    ckpt = CheckpointConfig(
        directory=os.path.join(artifacts_dir, "outputs", "checkpoints"),
        save_interval_steps=int(ckpt_spec.get("save_interval_steps", max(steps // 4, 1))),
        max_to_keep=int(ckpt_spec.get("max_to_keep", 3)),
        async_save=bool(ckpt_spec.get("async_save", True)),
    ) if spec.get("checkpoint", True) is not False else None

    # self-healing knobs (ISSUE 8; docs/RESILIENCE.md "Data-plane crash
    # matrix"): the watchdog is ON for every pod the runtime owns —
    # `watchdog: false` disables, `watchdog: {min_s: ..}` tunes
    wd_spec = spec.get("watchdog", True)
    wd_kw = wd_spec if isinstance(wd_spec, dict) else {}
    # multislice (ROADMAP item 3): the compiler injects num_slices from the
    # tpujob topology; MEGASCALE env is the fallback for hand-built specs
    num_slices = int(spec.get("num_slices",
                              os.environ.get("MEGASCALE_NUM_SLICES", 1)))
    tcfg = TrainerConfig(
        model=mcfg,
        optimizer=OptimizerConfig(
            name=spec.get("optimizer", "adamw"),
            learning_rate=float(spec.get("learning_rate", 3e-4)),
            warmup_steps=int(spec.get("warmup_steps", min(100, steps // 10 + 1))),
            total_steps=steps,
            schedule=spec.get("schedule", "cosine"),
            mu_dtype=spec.get("mu_dtype"),
            nu_dtype=spec.get("nu_dtype"),
        ),
        batch_size=batch_size,
        seq_len=seq_len,
        parallelism=spec.get("parallelism"),
        num_slices=num_slices,
        checkpoint=ckpt,
        log_interval=int(spec.get("log_interval", 10)),
        grad_dtype=spec.get("grad_dtype"),
        microbatches=int(spec.get("microbatches", 1)),
        accum_dtype=spec.get("accum_dtype"),
        anomaly_skip_budget=int(spec.get("anomaly_skip_budget", 3)),
        anomaly_rollback_budget=int(spec.get("anomaly_rollback_budget", 2)),
        watchdog=wd_spec is not False,
        watchdog_stall_factor=float(wd_kw.get("stall_factor", 10.0)),
        watchdog_min_s=float(wd_kw.get("min_s", 120.0)),
        watchdog_compile_grace_s=float(wd_kw.get("compile_grace_s", 1800.0)),
    )
    # Throughput bridge (ISSUE 5 tentpole (c)): on every tracked interval
    # the ThroughputMeter summary ALSO flows into run outputs, so the
    # dashboard and `bench.py --orchestrated` read live tokens/s / MFU /
    # step-time percentiles from the run itself — the same numbers the
    # terminal summary freezes at the end, not a bench-side recomputation.
    meter_keys = ("steps", "step_time_ms", "step_time_p50_ms",
                  "step_time_p95_ms", "tokens_per_sec",
                  "tokens_per_sec_per_chip", "achieved_tflops_per_chip",
                  "mfu")
    track = None
    if run is not None:
        def track(step, m):
            run.log_metrics(step=step, **{
                k: v for k, v in m.items() if isinstance(v, (int, float))
            })
            run.log_outputs(**{k: m[k] for k in meter_keys if k in m})
    # trainer-level chaos (ISSUE 8 tentpole (c)): hang/NaN/straggler
    # injection with budgets persisted in the artifacts dir so a
    # RESTARTED attempt runs clean — the self-healing proof, not a loop
    from ..resilience.chaos import TrainerChaos

    chaos = TrainerChaos.from_spec(spec.get("chaos"), state_dir=artifacts_dir)

    # per-step progress (ISSUE 8 tentpole (a)): rate-limited
    # progress.json publish + heartbeat-with-step so the control plane
    # can tell a slow run from a wedged one
    on_progress = None
    on_stalled = None
    log_line = None
    if run is not None:
        progress_interval = float(spec.get("progress_interval", 2.0))
        last_beat = [0.0]

        def on_progress(step, anomalies, rollbacks):
            now = time.monotonic()
            if now - last_beat[0] < progress_interval:
                return
            last_beat[0] = now
            run.report_progress(step, anomalies=dict(anomalies),
                                rollbacks=rollbacks)

        def on_stalled(step, waited, limit):
            # structured status condition + durable flush: the watchdog
            # hard-exits right after this, and the epitaph must survive
            run.log_status(
                "running", reason="TrainingStalled",
                message=f"no step completed for {waited:.1f}s "
                        f"(limit {limit:.1f}s, last step {step}); "
                        f"watchdog hard-exit -> retry budget")
            run.flush()

        def log_line(line):
            run.log_line(line)
            print(line, flush=True)

    # -- partition engine wiring (ISSUE 13) ---------------------------------
    # `lora:` wraps the task (frozen base + trainable adapters, optimizer
    # masked so the base costs zero moments); `partition_rules:` overlay
    # the built-in specs inside the Trainer; `import:` lands a foreign
    # checkpoint directly in sharded buffers after the mesh exists.
    lora_spec = spec.get("lora")
    import_spec = spec.get("import")
    partition_rules = spec.get("partition_rules")
    tx = None
    lora_cfg = None
    if lora_spec:
        if family not in ("lm", "mlm"):
            raise SystemExit(
                f"lora: is only supported for LM/MLM models (got {family})")
        from ..partition.lora import LoRAConfig, LoRATask, frozen_base_optimizer
        from ..train import make_optimizer

        lora_cfg = LoRAConfig.from_spec(lora_spec)
        task = LoRATask(task, lora_cfg)
        tx = frozen_base_optimizer(make_optimizer(tcfg.optimizer))

    # pod-side spans (ISSUE 5 tentpole (a)): first-step compile, train
    # window, checkpoint saves join the control-plane lifecycle timeline
    # through the trace id tracking picked up from POLYAXON_TRACE_ID
    trainer = Trainer(tcfg, task=task, track=track,
                      on_span=run.log_span if run is not None else None,
                      chaos=chaos, on_progress=on_progress,
                      on_stalled=on_stalled, log_line=log_line,
                      partition_rules=partition_rules, tx=tx)

    if run is not None:
        # partition-plan mirror (ISSUE 13 satellite): the same summary
        # `polyaxon partition plan` prints pre-launch, computed from the
        # trainer's RESOLVED shardings, lands in run outputs for the
        # dashboard — param count, bytes/device, axes actually used
        try:
            from ..partition import plan_summary_from_shardings

            abstract = jax.eval_shape(
                lambda k: trainer.task.init(k)[0], jax.random.PRNGKey(0))
            psum = plan_summary_from_shardings(
                abstract, trainer.param_shardings, trainer.mesh)
            psum["num_slices"] = num_slices
            run.log_outputs(partition_plan=psum)
        except Exception as e:  # never fail a run over a dashboard mirror
            print(f"[builtin] partition plan summary skipped: {e}",
                  flush=True)

    data_spec = dict(spec.get("data") or {})
    data_kwargs: dict[str, Any] = {}
    if vocab_size is not None:
        data_kwargs["vocab_size"] = vocab_size
    if image_size is not None:
        data_kwargs["image_size"] = image_size
    if num_classes is not None:
        data_kwargs["num_classes"] = num_classes
    data_cfg = DataConfig(
        kind=data_spec.get("kind", task.default_data_kind),
        batch_size=batch_size,
        seq_len=seq_len,
        path=data_spec.get("path"),
        seed=int(data_spec.get("seed", 0)),
        **data_kwargs,
    )
    batches = make_batches(data_cfg, trainer.mesh)

    # Preemption -> resume (docs/RESILIENCE.md): a restarted attempt shares
    # the artifacts dir, so restore_or_init picks up the latest checkpoint.
    # The data stream must be fast-forwarded to the restored step — without
    # this a resumed run re-consumes batches 0..k and diverges from an
    # uninterrupted run (the chaos parity proof would catch it). Seekable
    # sources (train/data.py) make this O(1): a step-100k resume no longer
    # replays 100k batches before training.
    from ..train.data import skip_batches

    # foreign-checkpoint import (ISSUE 13): ingest into sharded device
    # buffers through the rule engine — the Trainer's RESOLVED shardings
    # (built-ins + user overlay) decide placement, so a 7B tree never
    # materializes unsharded on one host. A latest complete checkpoint
    # still wins inside restore_or_init (resume beats re-import).
    init_params = None
    if import_spec and trainer.checkpointer is not None \
            and trainer.checkpointer.latest_complete_step() is not None:
        # resume beats re-import: a restarted attempt must not pay the
        # full foreign-tree read (minutes of I/O at 7B) only for
        # restore_or_init to overwrite it with the checkpoint
        print("[builtin] complete checkpoint found; skipping import",
              flush=True)
        import_spec = None
    if import_spec:
        if family not in ("lm", "mlm"):
            raise SystemExit(
                f"import: is only supported for LM/MLM models (got {family})")
        from ..partition import convert as pconvert

        base_shardings = (trainer.param_shardings["base"]
                          if lora_cfg is not None else trainer.param_shardings)
        imported = pconvert.import_params(
            import_spec["path"], mcfg, trainer.mesh,
            layout=import_spec.get("layout", "auto"),
            shardings=base_shardings,
            dtype=import_spec.get("dtype"),
            key_map=import_spec.get("key_map"),
            transpose=import_spec.get("transpose"),
        )
        if lora_cfg is not None:
            from ..partition.lora import init_lora

            adapters = init_lora(
                jax.random.PRNGKey(int(spec.get("seed", 0))),
                jax.tree.map(
                    lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
                    imported),
                lora_cfg)
            init_params = {"base": imported, "lora": adapters}
        else:
            init_params = imported

    # PBT exploit fork (ISSUE 19): the tuner pinned a parent trial's
    # checkpoint dir (+ optionally a step) in runtime.fork_from — restore
    # it READ-ONLY (the parent may still be training; a writer's purge
    # would delete its newer steps) and seed this member's state from the
    # parent's params through restore_or_init's init_params path, exactly
    # like a foreign-checkpoint import. Resume still beats re-fork: a
    # preempted fork that already saved its own checkpoint restores THAT.
    fork_spec = spec.get("fork_from")
    if fork_spec and trainer.checkpointer is not None \
            and trainer.checkpointer.latest_complete_step() is not None:
        print("[builtin] complete checkpoint found; skipping fork restore",
              flush=True)
        fork_spec = None
    if fork_spec:
        from ..train.checkpoint import CheckpointConfig as _CkptCfg
        from ..train.checkpoint import Checkpointer as _Ckpt

        ro = _Ckpt(_CkptCfg(directory=fork_spec["path"]), read_only=True)
        fork_step = fork_spec.get("step")
        try:
            raw, restored = ro.restore_raw(
                step=int(fork_step) if fork_step is not None else None)
        except Exception as e:
            if fork_step is None:
                raise
            # the pinned step tore with the parent's preemption: fall
            # back to the parent's newest complete step rather than
            # failing the member
            raw, restored = ro.restore_raw()
            print(f"[builtin] fork step {fork_step} not restorable "
                  f"({e}); using parent step {restored}", flush=True)
        init_params = raw["params"] if isinstance(raw, dict) else raw.params
        print(f"[builtin] forked from {fork_spec['path']} @ step {restored}",
              flush=True)

    t_restore = time.time()
    state, start_step = trainer.restore_or_init(init_params=init_params)
    if run is not None:
        # zero-length-ish on a fresh start; on a resumed attempt this is
        # the checkpoint-read cost the timeline should surface
        run.log_span("restore", t_restore, time.time(),
                     resumed_from_step=int(start_step))
    skip_batches(batches, start_step)

    # host/TPU resource telemetry (upstream traceml's ResourceLogger ran in
    # the sidecar by default): metrics land in the run's event files under
    # host_*/tpu_* names, charted in the dashboard's Resources section.
    # `resources: false` disables; `resources: {interval: N}` tunes.
    res_spec = spec.get("resources", True)
    res_logger = None
    if run is not None and res_spec is not False:
        interval = (float(res_spec.get("interval", 10.0))
                    if isinstance(res_spec, dict) else 10.0)
        res_logger = tracking.ResourceLogger(run, interval=interval).start()

    from ..train.trainer import TrainingDivergedError

    try:
        profile = spec.get("profile")
        if profile:
            # Warm up (compile + first steps), then trace a few real steps
            # into the run's artifacts. EVERY process runs the same fit
            # structure — fit() ends with a checkpoint save, an orbax
            # cross-process collective, so diverging here would deadlock
            # multi-host runs. Only process 0 wraps the middle segment in
            # the profiler.
            prof_steps = int(profile.get("steps", 3)) if isinstance(profile, dict) else 3
            warm = max(min(2, steps), start_step)
            state, metrics = trainer.fit(batches, num_steps=warm, state=state)
            prof_dir = os.path.join(artifacts_dir, "outputs", "profile")
            end = min(warm + prof_steps, steps)
            if end > warm:
                if is_primary:
                    with jax.profiler.trace(prof_dir):
                        state, metrics = trainer.fit(batches, num_steps=end, state=state)
                else:
                    state, metrics = trainer.fit(batches, num_steps=end, state=state)
            if end < steps:
                state, metrics = trainer.fit(batches, num_steps=steps, state=state)
            if run is not None:
                run.log_artifact("profile", "outputs/profile", kind="profile")
        else:
            state, metrics = trainer.fit(batches, num_steps=steps, state=state)
    except TrainingDivergedError as e:
        # fail the run LOUDLY with the anomaly history in outputs (ISSUE 8
        # tentpole (b)): the budgets are gone, so retrying silently would
        # just burn chips re-diverging — an operator needs the trail
        if run is not None:
            run.log_outputs(
                diverged=True,
                train_anomalies_loss=int(e.anomalies.get("loss", 0)),
                train_anomalies_grad=int(e.anomalies.get("grad", 0)),
                train_rollbacks=int(e.rollbacks),
                anomaly_history=e.history,
                resumed_from_step=int(start_step))
            run.log_status("failed", reason="TrainingDiverged",
                           message=str(e))
            run.end()
        raise SystemExit(f"training diverged: {e}")
    finally:
        # a failing fit must not leak the telemetry thread (it would keep
        # writing events for a dead run until process exit)
        if res_logger is not None:
            res_logger.stop()
    summary = {k: v for k, v in metrics.items() if isinstance(v, (int, float))}
    # which checkpoint step this attempt started from (0 = fresh): the
    # preemption->resume proof asserts a restarted attempt reports > 0
    summary["resumed_from_step"] = int(start_step)
    if run is not None:
        # final progress beat: the store's heartbeat_step lands on the
        # terminal step and the train_* counter deltas are fully flushed
        run.report_progress(
            steps,
            anomalies={"loss": summary.get("train_anomalies_loss", 0),
                       "grad": summary.get("train_anomalies_grad", 0)},
            rollbacks=int(summary.get("train_rollbacks", 0)))
        run.log_outputs(**summary)
        if ckpt:
            run.log_artifact("checkpoints", "outputs/checkpoints", kind="checkpoint")
        run.end()
    print(json.dumps({"final": summary}))
    return summary


def main() -> None:
    raw = os.environ.get("PLX_BUILTIN_SPEC")
    if not raw:
        raise SystemExit("PLX_BUILTIN_SPEC not set")
    run_builtin(json.loads(raw))


if __name__ == "__main__":
    main()
