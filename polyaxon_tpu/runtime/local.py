"""Local executor: runs a resolved operation as a subprocess with the run
context layout — the "fake cluster" execution backend (SURVEY.md §4
"Integration/e2e": in-proc scheduler + subprocess pods). Also the `--local`
CLI path (SURVEY.md §7 stage 2 minimum e2e slice).

Responsibilities mirrored from the pod runtime (SURVEY.md §3a step 6):
  init steps -> main process (stdout/err captured to logs/) -> final status;
  a sidecar thread syncs outputs to a remote store when one is configured.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Optional

from ..compiler.converter import LocalPayload
from ..schemas.statuses import V1Statuses
from ..tracking.writer import LogWriter
from .init import InitError, run_init_step


def _with_pythonpath(env: dict) -> dict:
    """Prepend the framework source root to the (already merged) child env's
    PYTHONPATH so the package is importable without being installed, while
    preserving any PYTHONPATH the operation's env spec set."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    existing = env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        env["PYTHONPATH"] = f"{pkg_root}{os.pathsep}{existing}" if existing else pkg_root
    return env


def pod_base_env() -> dict:
    """The inherited (os.environ) half of a pod's env, with any forced XLA
    host device count dropped: pods declare their own device topology
    (runtime spec ``num_cpu_devices``), and a test harness forcing an
    8-device mesh on ITS process must not hand every "host" 8 devices.
    Applied BEFORE the operation's env spec merges in, so a pod that
    explicitly sets XLA_FLAGS keeps exactly what it asked for."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        kept = [t for t in flags.split()
                if "xla_force_host_platform_device_count" not in t]
        if kept:
            env["XLA_FLAGS"] = " ".join(kept)
        else:
            env.pop("XLA_FLAGS", None)
    return env


class LocalExecution:
    """Handle on a launched local run."""

    def __init__(self, payload: LocalPayload, proc: Optional[subprocess.Popen], thread: Optional[threading.Thread]):
        self.payload = payload
        self.proc = proc
        self.thread = thread
        self.returncode: Optional[int] = None

    def wait(self, timeout: Optional[float] = None) -> int:
        if self.thread is not None:
            self.thread.join(timeout)
            if self.thread.is_alive():
                raise TimeoutError("run still active")
        return self.returncode if self.returncode is not None else -1

    def stop(self) -> None:
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class LocalExecutor:
    """Executes LocalPayloads; reports status via a callback (the store's
    ``transition`` or a RunClient's ``log_status``)."""

    def __init__(
        self,
        on_status: Optional[Callable[[str, str, Optional[str]], None]] = None,
        remote_store: Optional[str] = None,
        sync_interval: float = 5.0,
        retry=None,
    ):
        from ..resilience.retry import DEFAULT_HTTP_RETRY

        # on_status(run_uuid, status, message)
        self.on_status = on_status or (lambda *a: None)
        self.remote_store = remote_store
        self.sync_interval = sync_interval
        # transient-failure policy for the sidecar's artifact sync
        self.retry = retry if retry is not None else DEFAULT_HTTP_RETRY

    # -- submit ------------------------------------------------------------

    def submit(self, payload: LocalPayload, block: bool = False) -> LocalExecution:
        execution = LocalExecution(payload, None, None)
        thread = threading.Thread(target=self._run, args=(payload, execution), daemon=True)
        execution.thread = thread
        thread.start()
        if block:
            execution.wait(payload.timeout)
        return execution

    # -- the pod lifecycle -------------------------------------------------

    def _run(self, payload: LocalPayload, execution: LocalExecution) -> None:
        uuid = payload.run_uuid
        run_dir = payload.artifacts_path
        os.makedirs(run_dir, exist_ok=True)
        log = LogWriter(run_dir)
        attempts = payload.max_retries + 1
        try:
            self.on_status(uuid, V1Statuses.STARTING.value, None)
            for step in payload.init:
                run_init_step(step, run_dir)
        except InitError as e:
            log.write(f"[init] failed: {e}")
            log.close()
            self.on_status(uuid, V1Statuses.FAILED.value, f"init failed: {e}")
            execution.returncode = 1
            return

        status, rc, msg = V1Statuses.FAILED.value, 1, None
        for attempt in range(attempts):
            if attempt:
                self.on_status(uuid, V1Statuses.RETRYING.value, f"attempt {attempt + 1}")
                self.on_status(uuid, V1Statuses.QUEUED.value, None)
                self.on_status(uuid, V1Statuses.SCHEDULED.value, None)
                self.on_status(uuid, V1Statuses.STARTING.value, None)
            self.on_status(uuid, V1Statuses.RUNNING.value, None)
            stop_sync = threading.Event()
            sync_thread = self._start_sidecar(payload, stop_sync)
            try:
                rc = self._run_main(payload, execution, log)
            finally:
                stop_sync.set()
                if sync_thread:
                    sync_thread.join(timeout=30)
            if rc == 0:
                status, msg = V1Statuses.SUCCEEDED.value, None
                break
            status, msg = V1Statuses.FAILED.value, f"exit code {rc}"
        log.close()
        execution.returncode = rc
        self.on_status(uuid, status, msg)

    def _run_main(self, payload: LocalPayload, execution: LocalExecution, log: LogWriter) -> int:
        if payload.serve is not None:
            return self._run_serve(payload, execution, log)
        if payload.builtin is not None:
            return self._run_builtin(payload, execution, log)
        if not payload.argv:
            log.write("[main] no container command; nothing to run")
            return 0
        env = _with_pythonpath({**pod_base_env(), **payload.env})
        workdir = payload.workdir or os.path.join(payload.artifacts_path, "code")
        if not os.path.isdir(workdir):
            workdir = payload.artifacts_path
        return self._spawn_and_pump(payload, execution, log, payload.argv, env, workdir)

    def _run_serve(self, payload: LocalPayload, execution: LocalExecution, log: LogWriter) -> int:
        """Service `runtime:` shortcut — the built-in inference engine
        (serve/runtime.py) in a subprocess, same isolation contract as the
        trainer."""
        import json

        env = _with_pythonpath({**pod_base_env(), **payload.env})
        env["PLX_SERVE_SPEC"] = json.dumps(dict(payload.serve or {}))
        env.setdefault("PLX_REPLICA_INDEX", "0")
        argv = [sys.executable, "-m", "polyaxon_tpu.serve.runtime"]
        return self._spawn_and_pump(payload, execution, log, argv, env, payload.artifacts_path)

    def _run_builtin(self, payload: LocalPayload, execution: LocalExecution, log: LogWriter) -> int:
        """`runtime:` shortcut — run the built-in trainer in a subprocess so
        crashes/OOMs behave like user containers."""
        import json

        spec = dict(payload.builtin or {})
        env = _with_pythonpath({**pod_base_env(), **payload.env})
        env["PLX_BUILTIN_SPEC"] = json.dumps(spec)
        argv = [sys.executable, "-m", "polyaxon_tpu.runtime.builtin"]
        return self._spawn_and_pump(payload, execution, log, argv, env, payload.artifacts_path)

    def _spawn_and_pump(
        self,
        payload: LocalPayload,
        execution: LocalExecution,
        log: LogWriter,
        argv: list,
        env: dict,
        workdir: str,
    ) -> int:
        proc = subprocess.Popen(
            argv,
            env=env,
            cwd=workdir,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        # expose the live proc so stop() (agent _do_stop, tuner early stop)
        # can actually kill the run instead of only flipping its status
        execution.proc = proc
        # watchdog, not an in-loop check: a hung process that prints nothing
        # must still be killed at the deadline
        watchdog: Optional[threading.Timer] = None
        if payload.timeout:
            def _kill():
                if proc.poll() is None:
                    log.write("[main] timeout exceeded; terminated")
                    proc.terminate()

            watchdog = threading.Timer(payload.timeout, _kill)
            watchdog.daemon = True
            watchdog.start()
        try:
            for line in proc.stdout:  # type: ignore[union-attr]
                log.write(line)
            return proc.wait()
        finally:
            if watchdog:
                watchdog.cancel()

    # -- sidecar -----------------------------------------------------------

    def _start_sidecar(self, payload: LocalPayload, stop: threading.Event) -> Optional[threading.Thread]:
        if not self.remote_store:
            return None
        from ..fs import sync_dir

        remote = os.path.join(self.remote_store, payload.project, payload.run_uuid)

        def loop():
            while not stop.wait(self.sync_interval):
                try:
                    # retried within the policy budget; a sync that still
                    # fails skips this interval instead of killing the
                    # sidecar thread (the next interval tries again)
                    self.retry.call(sync_dir, payload.artifacts_path, remote)
                except Exception:
                    import traceback

                    traceback.print_exc()
            self.retry.call(sync_dir, payload.artifacts_path, remote)  # final sync

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t
