"""Artifact-store IO over fsspec (upstream ``polyaxon._fs`` — SURVEY.md §2
"FS / connections" row): gs://, s3://, or plain local paths, resolved from
``V1Connection`` specs."""

from .fs import download, get_fs, get_fs_from_connection, sync_dir, upload

__all__ = ["download", "get_fs", "get_fs_from_connection", "sync_dir", "upload"]
