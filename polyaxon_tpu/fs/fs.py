"""fsspec-backed store operations used by init/sidecar/checkpoint paths."""

from __future__ import annotations

import os
from typing import Any, Optional

import fsspec

from ..schemas.connections import V1Connection


def get_fs(url_or_path: str) -> tuple[Any, str]:
    """Returns (filesystem, path-without-protocol)."""
    if "://" in url_or_path:
        protocol, _, rest = url_or_path.partition("://")
        return fsspec.filesystem(protocol), rest
    return fsspec.filesystem("file"), url_or_path


def get_fs_from_connection(conn: V1Connection) -> tuple[Any, str]:
    """Resolve a declared connection to (filesystem, root path)."""
    root = conn.store_path()
    if conn.kind in ("gcs", "s3", "wasb"):
        proto = {"gcs": "gs", "s3": "s3", "wasb": "abfs"}[conn.kind]
        return fsspec.filesystem(proto), root
    if conn.kind in ("volume_claim", "host_path"):
        return fsspec.filesystem("file"), root or "/"
    raise ValueError(f"No fs mapping for connection kind {conn.kind!r}")


def download(src: str, dest: str) -> str:
    fs, path = get_fs(src)
    if fs.isdir(path):
        fs.get(path, dest, recursive=True)
    else:
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        fs.get(path, dest)
    return dest


def upload(src: str, dest: str) -> str:
    fs, path = get_fs(dest)
    if os.path.isdir(src):
        fs.put(src, path, recursive=True)
    else:
        fs.put(src, path)
    return dest


def _remote_mtime(rinfo: dict) -> Optional[float]:
    for key in ("mtime", "LastModified", "last_modified", "updated"):
        v = rinfo.get(key)
        if v is None:
            continue
        if hasattr(v, "timestamp"):
            return v.timestamp()
        try:
            return float(v)
        except (TypeError, ValueError):
            continue
    return None


def sync_dir(local_dir: str, remote_dir: str, exclude: Optional[set[str]] = None) -> int:
    """One-way sync local->remote of files newer than the remote copy (the
    sidecar loop's primitive — SURVEY.md §2 "Sidecar"). A file is skipped
    only when sizes match AND the remote copy is at least as new (same-size
    in-place rewrites must still sync). Returns files copied."""
    fs, rroot = get_fs(remote_dir)
    copied = 0
    for root, _, files in os.walk(local_dir):
        for f in files:
            if exclude and f in exclude:
                continue
            lpath = os.path.join(root, f)
            rel = os.path.relpath(lpath, local_dir)
            rpath = os.path.join(rroot, rel)
            try:
                rinfo = fs.info(rpath)
                if rinfo.get("size") == os.path.getsize(lpath):
                    rm = _remote_mtime(rinfo)
                    if rm is not None and rm >= os.path.getmtime(lpath):
                        continue
            except FileNotFoundError:
                pass
            fs.makedirs(os.path.dirname(rpath), exist_ok=True)
            fs.put(lpath, rpath)
            copied += 1
    return copied
