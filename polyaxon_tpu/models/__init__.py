"""Model zoo: the training workloads the BASELINE configs name (SURVEY.md §6)
— Llama-2 (flagship), GPT-2, BERT, ViT, ResNet — as pure-pytree JAX models
over the shared transformer core."""

from . import bert, gpt2, llama, resnet, transformer, vit
from .transformer import TransformerConfig, cross_entropy_loss, lm_loss_from_hidden

# name -> (family, config) for CLI/runtime lookup (`runtime: {model: ...}`);
# family selects the Task in train/tasks.py
REGISTRY: dict = {}
for _mod in (llama, gpt2):
    for _name, _cfg in _mod.CONFIGS.items():
        REGISTRY[_name] = ("lm", _cfg)
for _name, _cfg in bert.CONFIGS.items():
    REGISTRY[_name] = ("mlm", _cfg)
for _name, _cfg in vit.CONFIGS.items():
    REGISTRY[_name] = ("vit", _cfg)
for _name, _cfg in resnet.CONFIGS.items():
    REGISTRY[_name] = ("resnet", _cfg)

__all__ = [
    "bert", "gpt2", "llama", "resnet", "transformer", "vit",
    "TransformerConfig", "cross_entropy_loss", "lm_loss_from_hidden", "REGISTRY",
]
