"""Llama-2 family on the shared transformer core — the flagship runtime
(north star: Llama-2-7B pretraining on v5e-64 at ≥45% MFU, BASELINE.md)."""

from __future__ import annotations

from dataclasses import replace

import jax.numpy as jnp

from .transformer import TransformerConfig

# Llama-2 public architecture constants (params match meta-llama configs).
LLAMA2_7B = TransformerConfig(
    vocab_size=32000, hidden=4096, num_layers=32, num_heads=32,
    num_kv_heads=32, mlp_dim=11008, max_seq=4096, norm="rms", act="swiglu",
    pos="rope", causal=True, eps=1e-5, rope_theta=10000.0,
    dtype=jnp.bfloat16, remat="dots",
)

LLAMA2_13B = replace(LLAMA2_7B, hidden=5120, num_layers=40, num_heads=40,
                     num_kv_heads=40, mlp_dim=13824)

LLAMA2_70B = replace(LLAMA2_7B, hidden=8192, num_layers=80, num_heads=64,
                     num_kv_heads=8, mlp_dim=28672)

# Small configs for tests / CI / bench scaling studies.
LLAMA_TINY = replace(
    LLAMA2_7B, vocab_size=256, hidden=64, num_layers=2, num_heads=4,
    num_kv_heads=2, mlp_dim=128, max_seq=128, remat="none", dtype=jnp.float32,
    attn_impl="dense",
)

LLAMA_125M = replace(
    LLAMA2_7B, vocab_size=32000, hidden=768, num_layers=12, num_heads=12,
    num_kv_heads=12, mlp_dim=2048, max_seq=2048,
)

# ~1.1B with TinyLlama's architecture (hidden 2048, GQA 32/4, mlp 5632):
# the single-chip bench model — big enough that matmul shapes reach MXU
# efficiency (K=2048), small enough to fit one v5e-16GB with full AdamW
# (bf16 first moments) + remat.
LLAMA_1B = replace(
    LLAMA2_7B, hidden=2048, num_layers=22, num_heads=32, num_kv_heads=4,
    mlp_dim=5632, max_seq=2048,
)

# Mixtral-style sparse MoE (public 8x7B architecture constants): 8 experts,
# top-2 routing, otherwise the 7B trunk with GQA 32/8 and 32k context.
# Experts shard over the `expert` mesh axis (EP).
MIXTRAL_8X7B = replace(
    LLAMA2_7B, vocab_size=32000, hidden=4096, num_layers=32, num_heads=32,
    num_kv_heads=8, mlp_dim=14336, max_seq=32768, rope_theta=1e6,
    num_experts=8, expert_top_k=2,
)

LLAMA_MOE_TINY = replace(
    LLAMA_TINY, num_experts=4, expert_top_k=2, mlp_dim=64,
)

# ~1.2B-total / ~0.4B-active sparse MoE sized for one 16 GiB chip with
# full AdamW (bf16 moments) — the single-chip MoE bench model (VERDICT r3
# #6: measure the dispatch, don't just dryrun it).
LLAMA_MOE_1B = replace(
    LLAMA2_7B, hidden=1024, num_layers=16, num_heads=16, num_kv_heads=4,
    mlp_dim=2560, max_seq=2048, num_experts=8, expert_top_k=2,
)

CONFIGS = {
    "llama2-7b": LLAMA2_7B,
    "llama2-13b": LLAMA2_13B,
    "llama2-70b": LLAMA2_70B,
    "llama-tiny": LLAMA_TINY,
    "llama-125m": LLAMA_125M,
    "llama-1b": LLAMA_1B,
    "mixtral-8x7b": MIXTRAL_8X7B,
    "llama-moe-tiny": LLAMA_MOE_TINY,
    "llama-moe-1b": LLAMA_MOE_1B,
}
