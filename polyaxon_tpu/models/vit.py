"""ViT-B/16 (BASELINE config 5: 16 parallel ViT-B/16 Hyperband trials).

Patchify is a reshape + matmul rather than a conv — for non-overlapping
patches they're identical, and the matmul form feeds the MXU directly with
no im2col. Encoder rides the shared transformer core (causal=False) via
``inputs_embeds``; adds a CLS token and a classification head.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp

from . import transformer
from .transformer import TransformerConfig
from ..parallel.mesh import ShardingRules


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    channels: int = 3
    encoder: TransformerConfig = None  # type: ignore[assignment]

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size ** 2

    def num_params(self) -> int:
        h = self.encoder.hidden
        # drop the encoder's token-embed and learned-pos terms (init() deletes
        # tokens and replaces pos with the patch-grid table)
        enc = self.encoder.num_params() - self.encoder.vocab_size * h \
            - self.encoder.max_seq * h
        pos = (self.num_patches + 1) * h
        patch = self.patch_dim * h + h
        cls = h
        head = h * self.num_classes + self.num_classes
        return enc + pos + patch + cls + head


def _encoder(hidden, layers, heads, mlp, seq) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=1,  # unused: inputs_embeds path
        hidden=hidden, num_layers=layers, num_heads=heads, mlp_dim=mlp,
        max_seq=seq, norm="ln", act="gelu", pos="learned", causal=False,
        use_bias=True, tie_embeddings=True, eps=1e-6, dtype=jnp.bfloat16,
    )


VIT_B16 = ViTConfig(encoder=_encoder(768, 12, 12, 3072, 197))
VIT_L16 = ViTConfig(encoder=_encoder(1024, 24, 16, 4096, 197))
VIT_TINY = ViTConfig(
    image_size=32, patch_size=8, num_classes=10,
    encoder=replace(_encoder(64, 2, 4, 128, 17), dtype=jnp.float32, attn_impl="dense"),
)

CONFIGS = {"vit-b16": VIT_B16, "vit-l16": VIT_L16, "vit-tiny": VIT_TINY}


def init(key: jax.Array, cfg: ViTConfig) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    enc = transformer.init(k1, cfg.encoder)
    h = cfg.encoder.hidden
    del enc["embed"]["tokens"]
    enc["embed"]["pos"] = (
        jax.random.truncated_normal(k2, -2, 2, (cfg.num_patches + 1, h), jnp.float32) * 0.02
    )
    return {
        "encoder": enc,
        "patch": {"w": jax.random.truncated_normal(k3, -2, 2, (cfg.patch_dim, h), jnp.float32) * 0.02,
                  "b": jnp.zeros((h,), jnp.float32)},
        "cls": jnp.zeros((1, 1, h), jnp.float32),
        "head": {"w": jax.random.truncated_normal(k4, -2, 2, (h, cfg.num_classes), jnp.float32) * 0.02,
                 "b": jnp.zeros((cfg.num_classes,), jnp.float32)},
    }


def param_specs(cfg: ViTConfig, rules: Optional[ShardingRules] = None):
    rules = rules or ShardingRules()
    enc = transformer.param_specs(cfg.encoder, rules)
    del enc["embed"]["tokens"]
    enc["embed"]["pos"] = rules.spec((None, "embed"))
    return {
        "encoder": enc,
        "patch": {"w": rules.spec((None, "embed")), "b": rules.spec((None,))},
        "cls": rules.spec((None, None, None)),
        "head": {"w": rules.spec(("embed", "classes")), "b": rules.spec(("classes",))},
    }


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """[B, H, W, C] -> [B, (H/p)*(W/p), p*p*C]"""
    b, hh, ww, c = images.shape
    x = images.reshape(b, hh // patch, patch, ww // patch, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (hh // patch) * (ww // patch), patch * patch * c)


def apply(params: dict, images: jax.Array, cfg: ViTConfig, *, mesh=None, interpret=None) -> jax.Array:
    """images [B, H, W, C] -> class logits [B, num_classes] (f32)."""
    dt = cfg.encoder.dtype
    x = patchify(images.astype(dt), cfg.patch_size)
    x = x @ params["patch"]["w"].astype(dt) + params["patch"]["b"].astype(dt)
    cls = jnp.broadcast_to(params["cls"].astype(dt), (x.shape[0], 1, x.shape[-1]))
    x = jnp.concatenate([cls, x], axis=1)
    feats = _encode(params["encoder"], x, cfg, mesh, interpret)
    cls_out = feats[:, 0]
    return (cls_out @ params["head"]["w"].astype(dt) + params["head"]["b"].astype(dt)).astype(jnp.float32)


def _encode(enc_params, x, cfg: ViTConfig, mesh, interpret):
    """Run the transformer trunk on embeddings, skipping the LM head
    (shares run_trunk with the LM models, so every remat policy and the
    GPipe stage path apply to ViT too)."""
    ecfg = cfg.encoder
    s = x.shape[1]
    x = x + enc_params["embed"]["pos"].astype(ecfg.dtype)[None, :s]
    x, _aux = transformer.run_trunk(
        x, enc_params["layers"], ecfg, None, mesh, interpret)
    return transformer._norm(x, enc_params["final_norm"], ecfg)


def classification_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return (logz - gold).mean()
