"""ResNet-50 (BASELINE config 2: 4-replica DDP ResNet-50/CIFAR-10 → here a
``data``-axis mesh program). NHWC layout (TPU-native), lax convs, explicit
BatchNorm state threading (pure pytrees, no mutable modules)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: object = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    small_inputs: bool = False  # CIFAR: 3x3 stem, no maxpool


RESNET50 = ResNetConfig()
RESNET50_CIFAR = ResNetConfig(num_classes=10, small_inputs=True)
RESNET18_CIFAR = ResNetConfig(stage_sizes=(2, 2, 2, 2), num_classes=10,
                              small_inputs=True, width=16)

CONFIGS = {"resnet50": RESNET50, "resnet50-cifar": RESNET50_CIFAR,
           "resnet18-cifar": RESNET18_CIFAR}

_BOTTLENECK = 4


def _conv_shape(kh, kw, cin, cout):
    return (kh, kw, cin, cout)


def init(key: jax.Array, cfg: ResNetConfig) -> tuple[dict, dict]:
    """Returns (params, batch_stats)."""
    params: dict = {}
    stats: dict = {}
    keys = iter(jax.random.split(key, 256))

    def conv(name, kh, kw, cin, cout):
        fan = kh * kw * cin
        params[name] = {"w": jax.random.normal(next(keys), _conv_shape(kh, kw, cin, cout),
                                               jnp.float32) * (2.0 / fan) ** 0.5}

    def bn(name, c):
        params[name] = {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}
        stats[name] = {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}

    w = cfg.width
    stem_k = 3 if cfg.small_inputs else 7
    conv("stem", stem_k, stem_k, 3, w)
    bn("stem_bn", w)
    cin = w
    for si, n_blocks in enumerate(cfg.stage_sizes):
        cmid = w * (2 ** si)
        cout = cmid * _BOTTLENECK
        for bi in range(n_blocks):
            pre = f"s{si}b{bi}"
            conv(f"{pre}_c1", 1, 1, cin, cmid); bn(f"{pre}_bn1", cmid)
            conv(f"{pre}_c2", 3, 3, cmid, cmid); bn(f"{pre}_bn2", cmid)
            conv(f"{pre}_c3", 1, 1, cmid, cout); bn(f"{pre}_bn3", cout)
            if bi == 0:
                conv(f"{pre}_proj", 1, 1, cin, cout); bn(f"{pre}_projbn", cout)
            cin = cout
    params["head"] = {
        "w": jax.random.normal(next(keys), (cin, cfg.num_classes), jnp.float32) * 0.01,
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params, stats


def _conv(x, p, stride=1):
    return jax.lax.conv_general_dilated(
        x, p["w"].astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, p, s, cfg, train, new_stats, name):
    if train:
        mean = jnp.mean(x.astype(jnp.float32), axis=(0, 1, 2))
        var = jnp.var(x.astype(jnp.float32), axis=(0, 1, 2))
        m = cfg.bn_momentum
        new_stats[name] = {
            "mean": m * s[name]["mean"] + (1 - m) * mean,
            "var": m * s[name]["var"] + (1 - m) * var,
        }
    else:
        mean, var = s[name]["mean"], s[name]["var"]
    inv = jax.lax.rsqrt(var + cfg.bn_eps)
    out = (x.astype(jnp.float32) - mean) * inv * p[name]["scale"] + p[name]["bias"]
    return out.astype(x.dtype)


def apply(
    params: dict, stats: dict, images: jax.Array, cfg: ResNetConfig,
    *, train: bool = True,
) -> tuple[jax.Array, dict]:
    """images [B,H,W,3] -> (logits [B,classes] f32, updated batch_stats)."""
    new_stats: dict = dict(stats)
    x = images.astype(cfg.dtype)
    x = _conv(x, params["stem"], stride=1 if cfg.small_inputs else 2)
    x = jax.nn.relu(_bn(x, params, stats, cfg, train, new_stats, "stem_bn"))
    if not cfg.small_inputs:
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for si, n_blocks in enumerate(cfg.stage_sizes):
        for bi in range(n_blocks):
            pre = f"s{si}b{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            residual = x
            y = _conv(x, params[f"{pre}_c1"])
            y = jax.nn.relu(_bn(y, params, stats, cfg, train, new_stats, f"{pre}_bn1"))
            y = _conv(y, params[f"{pre}_c2"], stride=stride)
            y = jax.nn.relu(_bn(y, params, stats, cfg, train, new_stats, f"{pre}_bn2"))
            y = _conv(y, params[f"{pre}_c3"])
            y = _bn(y, params, stats, cfg, train, new_stats, f"{pre}_bn3")
            if f"{pre}_proj" in params:
                residual = _conv(x, params[f"{pre}_proj"], stride=stride)
                residual = _bn(residual, params, stats, cfg, train, new_stats, f"{pre}_projbn")
            x = jax.nn.relu(y + residual)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits.astype(jnp.float32), new_stats


def flops_per_image(cfg: ResNetConfig, image_size: int) -> float:
    """Training FLOPs per image (2*MACs forward, x3 for fwd+bwd), walking
    the same conv schedule as apply()."""
    total = 0.0

    def conv(kh, kw, cin, cout, hw, stride=1):
        nonlocal total
        out = hw // stride
        total += 2.0 * kh * kw * cin * cout * out * out
        return out

    w = cfg.width
    hw = image_size
    hw = conv(3 if cfg.small_inputs else 7, 3 if cfg.small_inputs else 7, 3, w,
              hw, stride=1 if cfg.small_inputs else 2)
    if not cfg.small_inputs:
        hw //= 2  # maxpool
    cin = w
    for si, n_blocks in enumerate(cfg.stage_sizes):
        cmid = w * (2 ** si)
        cout = cmid * _BOTTLENECK
        for bi in range(n_blocks):
            stride = 2 if (bi == 0 and si > 0) else 1
            conv(1, 1, cin, cmid, hw)
            hw2 = conv(3, 3, cmid, cmid, hw, stride=stride)
            conv(1, 1, cmid, cout, hw2)
            if bi == 0:
                conv(1, 1, cin, cout, hw, stride=stride)
            hw = hw2
            cin = cout
    total += 2.0 * cin * cfg.num_classes
    return 3.0 * total


def classification_loss(logits, labels):
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return (logz - gold).mean()
