"""GPT-2 family (BASELINE config 4: Horovod GPT-2-345M → same model, ICI
allreduce). Learned positions, pre-LN, GELU, biases, tied embeddings."""

from __future__ import annotations

from dataclasses import replace

import jax.numpy as jnp

from .transformer import TransformerConfig

_BASE = dict(
    vocab_size=50257, max_seq=1024, norm="ln", act="gelu", pos="learned",
    causal=True, use_bias=True, tie_embeddings=True, eps=1e-5,
    dtype=jnp.bfloat16,
)

GPT2_124M = TransformerConfig(hidden=768, num_layers=12, num_heads=12, mlp_dim=3072, **_BASE)
GPT2_345M = TransformerConfig(hidden=1024, num_layers=24, num_heads=16, mlp_dim=4096, **_BASE)
GPT2_774M = TransformerConfig(hidden=1280, num_layers=36, num_heads=20, mlp_dim=5120, **_BASE)
GPT2_1558M = TransformerConfig(hidden=1600, num_layers=48, num_heads=25, mlp_dim=6400, **_BASE)

GPT2_TINY = replace(
    GPT2_124M, vocab_size=256, hidden=64, num_layers=2, num_heads=4,
    mlp_dim=128, max_seq=128, dtype=jnp.float32, attn_impl="dense",
)

CONFIGS = {
    "gpt2-124m": GPT2_124M,
    "gpt2-345m": GPT2_345M,
    "gpt2-774m": GPT2_774M,
    "gpt2-1558m": GPT2_1558M,
    "gpt2-tiny": GPT2_TINY,
}
