"""Shared transformer core for the model zoo (Llama/GPT-2/BERT/ViT).

The reference contains no model code (SURVEY.md §1: "What Polyaxon does not
contain") — this runtime is the capability the north star adds. Design is
TPU-first, not a torch translation:

- **pure pytrees**: params are nested dicts of arrays; every leaf carries
  logical axis names so `parallel.ShardingRules` decides placement without
  touching model code.
- **scan over stacked layers**: one compiled layer body regardless of depth
  (compile time + XLA fusion), with `jax.checkpoint` remat inside the scan
  body to trade FLOPs for HBM.
- **sharded attention via shard_map**: the pallas kernel runs on local
  shards (batch over data/fsdp, heads over model, sequence over context);
  ring attention engages automatically when the context axis is >1.
- **bf16 activations, f32 params/optimizer** by default; logits and
  softmax in f32.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, PartitionSpec as P
from ..parallel.compat import shard_map

from ..ops import (
    apply_rope,
    attention,
    dense_attention,
    gated as _gated,
    gelu,
    layer_norm,
    repeat_kv,
    ring_attention,
    rms_norm,
    rope_frequencies,
    swiglu,
    ulysses_attention,
)
from ..parallel.mesh import ShardingRules


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int
    hidden: int
    num_layers: int
    num_heads: int
    mlp_dim: int
    num_kv_heads: Optional[int] = None          # GQA; defaults to num_heads
    head_dim: Optional[int] = None              # defaults to hidden // num_heads
    max_seq: int = 2048
    norm: str = "rms"                           # "rms" | "ln"
    act: str = "swiglu"                         # "swiglu" | "gelu"
    pos: str = "rope"                           # "rope" | "learned" | "none"
    causal: bool = True
    use_bias: bool = False                      # linear/ln biases (GPT-2/BERT)
    tie_embeddings: bool = False
    rope_theta: float = 10000.0
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16                   # activation dtype
    param_dtype: Any = jnp.float32
    attn_impl: str = "auto"                     # "auto" | "dense" | "flash"
    seq_parallel: str = "ring"                  # "ring" | "ulysses" (context axis >1)
    remat: str = "none"             # "none" | "full" | "attn" | "attn_qkv" | "dots"
    attn_block_q: int = 512
    attn_block_k: int = 512
    # Backward flash blocks (dq/dkv kernels). 0 = inherit the fwd blocks.
    # The bwd streams two extra operands per step (do + row stats), so at
    # long sequence its VMEM-optimal aspect ratio differs from the fwd's.
    attn_block_q_bwd: int = 0
    attn_block_k_bwd: int = 0
    loss_chunk_tokens: int = 4096               # blockwise-CE chunk; 0 = unchunked
    pp_microbatches: int = 0                    # GPipe microbatches; 0 = 2*stages
    # Pipeline bubble-tick gating (parallel/pipeline.py): "auto" picks
    # "inner" when the stage body carries collectives (TP/CP/EP) and "full"
    # otherwise; "none" disables (the masked oracle — and the right choice
    # on CPU meshes, where XLA:CPU single-threads conditional bodies and
    # the gates measure SLOWER; see bench_artifacts/README.md r5. On TPU
    # gating saves the bubble FLOPs/energy at identical step time.)
    pp_gate: str = "auto"                       # "auto" | "full" | "inner" | "none"
    # 1F1B-style O(S) activation stash: each pipeline tick becomes a remat
    # island (recompute the stage forward during the backward sweep)
    # instead of the scan saving all O(M) microbatches' residuals. Trade
    # ~one extra stage forward per tick for an M/S-fold smaller stash.
    pp_remat_ticks: bool = False
    # Mixture-of-experts: >0 replaces each layer's MLP with num_experts
    # expert MLPs + a top-k router. Experts shard over the `expert` mesh
    # axis (EP). Dispatch:
    # - "capacity" (default): GShard/Switch-style — tokens scatter to their
    #   experts' fixed [E, capacity, h] buffers (static shapes for XLA;
    #   slot positions via a cumsum over the one-hot selection — no sort);
    #   capacity = tokens*k/E * capacity_factor, overflow tokens drop their
    #   overflowing assignment. Compute cost scales with top_k, not E.
    # - "dense": every expert computes every token, gates mask the combine —
    #   exact (no drops), cost scales with E; the parity oracle for tests.
    num_experts: int = 0
    expert_top_k: int = 2
    moe_dispatch: str = "capacity"              # "capacity" | "a2a" | "dense"
    expert_capacity_factor: float = 1.25
    # Capacity-dispatch streaming (round 6, VERDICT r5 #3): >0 blocks the
    # capacity dimension — gather → expert FFN → combine run per cap-chunk
    # of this size inside a rematerialized lax.scan, so the [E, cap, h]
    # dispatch buffers and the [E, cap, mlp] FFN intermediates never
    # materialize whole. 0 = one-shot dispatch (small models / oracle).
    moe_cap_block: int = 0
    # Switch-style load-balance aux loss coefficient (aux is 1.0 at perfect
    # balance and grows as routing collapses; added to the LM loss as
    # coef * mean-over-layers)
    router_aux_coef: float = 0.01

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def hd(self) -> int:
        return self.head_dim or self.hidden // self.num_heads

    def flops_per_token(self, seq_len: int) -> float:
        """Approximate training FLOPs/token (fwd+bwd = 6N_active + attention
        term); feeds the MFU meter (BASELINE.md metric). For MoE, N_active
        counts top_k experts, not all of them."""
        n_params = self.active_params()
        attn = 12 * self.num_layers * self.hidden * seq_len  # qk+av fwd+bwd
        return 6 * n_params + attn

    def active_params(self) -> int:
        """Params touched per token: == num_params() for dense; for MoE the
        per-layer expert block counts only top_k of num_experts experts."""
        total = self.num_params()
        if not self.num_experts:
            return total
        k = min(self.expert_top_k, self.num_experts)
        per_expert = (3 if self.act == "swiglu" else 2) * self.hidden * self.mlp_dim
        return total - self.num_layers * (self.num_experts - k) * per_expert

    def num_params(self) -> int:
        h, l = self.hidden, self.num_layers
        attn = h * self.num_heads * self.hd + 2 * h * self.kv_heads * self.hd \
            + self.num_heads * self.hd * h
        mlp = (3 if self.act == "swiglu" else 2) * h * self.mlp_dim
        if self.num_experts:
            mlp = self.num_experts * mlp + h * self.num_experts  # + router
        norms = (2 * l + 1) * h
        if self.norm == "ln" or self.use_bias:
            norms *= 2  # scale + bias
        biases = 0
        if self.use_bias:
            biases = l * (
                self.num_heads * self.hd + 2 * self.kv_heads * self.hd + h  # attn
                + self.mlp_dim + h  # mlp
            )
        embed = self.vocab_size * h * (1 if self.tie_embeddings else 2)
        pos = self.max_seq * h if self.pos == "learned" else 0
        return l * (attn + mlp) + norms + biases + embed + pos


# ---------------------------------------------------------------------------
# Parameter trees: shapes + logical axes live side by side
# ---------------------------------------------------------------------------


def _norm_params(cfg: TransformerConfig, layers: Optional[int] = None):
    lead = (layers,) if layers else ()
    lead_ax = ("layers",) if layers else ()
    p = {"scale": (lead + (cfg.hidden,), lead_ax + ("embed_act",))}
    if cfg.norm == "ln" or cfg.use_bias:
        p["bias"] = (lead + (cfg.hidden,), lead_ax + ("embed_act",))
    return p


def abstract_params(cfg: TransformerConfig) -> dict:
    """Returns a pytree whose leaves are (shape, logical_axes) tuples."""
    h, nh, kvh, hd, mlp, L = cfg.hidden, cfg.num_heads, cfg.kv_heads, cfg.hd, cfg.mlp_dim, cfg.num_layers
    layer = {
        "attn_norm": _norm_params(cfg, L),
        "mlp_norm": _norm_params(cfg, L),
        "attn": {
            "wq": ((L, h, nh, hd), ("layers", "embed", "heads", "head_dim")),
            "wk": ((L, h, kvh, hd), ("layers", "embed", "kv_heads", "head_dim")),
            "wv": ((L, h, kvh, hd), ("layers", "embed", "kv_heads", "head_dim")),
            "wo": ((L, nh, hd, h), ("layers", "heads", "head_dim", "embed")),
        },
    }
    if cfg.num_experts:
        E = cfg.num_experts
        layer["mlp"] = {
            "router": ((L, h, E), ("layers", "embed", None)),
            "wi": ((L, E, h, mlp), ("layers", "expert", "embed", "mlp")),
            "wo": ((L, E, mlp, h), ("layers", "expert", "mlp", "embed")),
        }
        if cfg.act == "swiglu":
            layer["mlp"]["wg"] = ((L, E, h, mlp), ("layers", "expert", "embed", "mlp"))
    else:
        layer["mlp"] = {
            "wi": ((L, h, mlp), ("layers", "embed", "mlp")),
            "wo": ((L, mlp, h), ("layers", "mlp", "embed")),
        }
        if cfg.act == "swiglu":
            layer["mlp"]["wg"] = ((L, h, mlp), ("layers", "embed", "mlp"))
    if cfg.use_bias:
        layer["attn"]["bq"] = ((L, nh, hd), ("layers", "heads", "head_dim"))
        layer["attn"]["bk"] = ((L, kvh, hd), ("layers", "kv_heads", "head_dim"))
        layer["attn"]["bv"] = ((L, kvh, hd), ("layers", "kv_heads", "head_dim"))
        layer["attn"]["bo"] = ((L, h), ("layers", "embed_act"))
        layer["mlp"]["bi"] = ((L, mlp), ("layers", "mlp"))
        layer["mlp"]["bo"] = ((L, h), ("layers", "embed_act"))
    if cfg.num_experts and cfg.use_bias:
        raise ValueError("MoE layers do not support use_bias")
    params = {
        "embed": {"tokens": ((cfg.vocab_size, h), ("vocab", "embed"))},
        "layers": layer,
        "final_norm": _norm_params(cfg),
    }
    if cfg.pos == "learned":
        params["embed"]["pos"] = ((cfg.max_seq, h), (None, "embed"))
    if not cfg.tie_embeddings:
        params["lm_head"] = {"w": ((h, cfg.vocab_size), ("embed", "vocab"))}
    return params


def _is_leaf(x):
    return isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], tuple)


def param_specs(cfg: TransformerConfig, rules: Optional[ShardingRules] = None):
    """PartitionSpec pytree matching init()'s params tree."""
    rules = rules or ShardingRules()
    return jax.tree.map(
        lambda ab: rules.spec(ab[1]), abstract_params(cfg), is_leaf=_is_leaf
    )


def init(key: jax.Array, cfg: TransformerConfig) -> dict:
    """Initialize params (f32 by default). Truncated-normal fan-in scaling;
    output projections scaled by 1/sqrt(2*L) (GPT-2 residual init)."""
    abstract = abstract_params(cfg)
    leaves, treedef = jax.tree.flatten(abstract, is_leaf=_is_leaf)
    keys = jax.random.split(key, len(leaves))
    paths = jax.tree_util.tree_flatten_with_path(abstract, is_leaf=_is_leaf)[0]

    def _init_leaf(k, path, ab):
        shape, axes = ab
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("scale",):
            return jnp.ones(shape, cfg.param_dtype)
        if name.startswith("b") or name == "bias":
            return jnp.zeros(shape, cfg.param_dtype)
        w = jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) * 0.02
        if name == "wo":  # residual-path projections
            w = w / (2 * cfg.num_layers) ** 0.5
        return w.astype(cfg.param_dtype)

    out = [_init_leaf(k, p, ab) for k, (p, ab) in zip(keys, paths)]
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _norm(x, p, cfg):
    if cfg.norm == "rms":
        return rms_norm(x, p["scale"], cfg.eps)
    return layer_norm(x, p["scale"], p.get("bias", jnp.zeros_like(p["scale"])), cfg.eps)


def _sharded_attention(q, k, v, cfg: TransformerConfig, mesh: Optional[Mesh], interpret=None):
    """Dispatch attention: local kernel, or shard_map'd over the mesh with
    ring/Ulysses on the context axis."""
    if mesh is None:
        return attention(
            q, k, v, causal=cfg.causal, impl=cfg.attn_impl,
            block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
            block_q_bwd=cfg.attn_block_q_bwd or None,
            block_k_bwd=cfg.attn_block_k_bwd or None, interpret=interpret,
        )
    cp = mesh.shape["context"]
    ring = cp > 1 and cfg.seq_parallel == "ring"
    if not (ring and k.shape[1] % mesh.shape["model"] == 0):
        # ring keeps GQA kv compact (expanded per visit inside the ring) as
        # long as the kv heads still divide over the model axis; every
        # other path — and TP degrees finer than the kv head count — wants
        # the q-head expansion up front
        k = repeat_kv(k, q.shape[1])
        v = repeat_kv(v, q.shape[1])
    qkv_spec = P(("data", "fsdp", "expert"), "model", "context", None)

    @functools.partial(
        shard_map, mesh=mesh, check_vma=False,
        in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec,
    )
    def _attn(q, k, v):
        if ring:
            return ring_attention(
                q, k, v, axis_name="context", axis_size=cp, causal=cfg.causal,
                block_q=min(cfg.attn_block_q, q.shape[2]),
                block_k=min(cfg.attn_block_k, k.shape[2]),
                interpret=interpret,
            )
        if cp > 1:
            return ulysses_attention(
                q, k, v, axis_name="context", causal=cfg.causal,
                impl=cfg.attn_impl, interpret=interpret,
            )
        return attention(
            q, k, v, causal=cfg.causal, impl=cfg.attn_impl,
            block_q=min(cfg.attn_block_q, q.shape[2]),
            block_k=min(cfg.attn_block_k, k.shape[2]),
            block_q_bwd=cfg.attn_block_q_bwd or None,
            block_k_bwd=cfg.attn_block_k_bwd or None,
            interpret=interpret,
        )

    return _attn(q, k, v)


@dataclass(frozen=True)
class InnerAxes:
    """Manual-collective mode for layer bodies running *inside* a shard_map
    (the pipeline): GSPMD constraints don't reach in there, so when the mesh
    has model/context axes the body psums its partial projections itself
    (tp), runs ring/Ulysses attention over the context axis (cp), and
    dispatches MoE tokens with the manual all-to-all over the expert axis
    (ep_size > 1; requires moe_dispatch="a2a")."""

    tp: bool = False
    cp: bool = False
    ep_size: int = 1


def _inner_attention(q, k, v, cfg: TransformerConfig, inner: InnerAxes,
                     interpret, active=None):
    """Attention for a device-local shard inside the pipeline shard_map:
    heads are already model-sharded; the context axis (if >1) runs ring or
    Ulysses exactly like the non-pipelined shard_map path. ``active`` gates
    the kernel launches on bubble ticks; ring/Ulysses run their
    ppermutes/all-to-alls unconditionally either way."""
    if inner.cp:
        if cfg.seq_parallel == "ring":
            # compact GQA kv rides the ring (ICI traffic / (heads/kv_heads));
            # ring_attention expands per visit
            return ring_attention(
                q, k, v, axis_name="context", causal=cfg.causal,
                block_q=min(cfg.attn_block_q, q.shape[2]),
                block_k=min(cfg.attn_block_k, k.shape[2]),
                interpret=interpret, active=active,
            )
        k = repeat_kv(k, q.shape[1])
        v = repeat_kv(v, q.shape[1])
        return ulysses_attention(
            q, k, v, axis_name="context", causal=cfg.causal,
            impl=cfg.attn_impl, interpret=interpret, active=active,
        )
    return _gated(active, lambda a, b, c: attention(
        a, b, c, causal=cfg.causal, impl=cfg.attn_impl,
        block_q=min(cfg.attn_block_q, q.shape[2]),
        block_k=min(cfg.attn_block_k, k.shape[2]),
        block_q_bwd=cfg.attn_block_q_bwd or None,
        block_k_bwd=cfg.attn_block_k_bwd or None, interpret=interpret,
    ), q, k, v)


def _save_flat(t, name):
    """checkpoint_name a [b, n, s, d] tensor in merged [b, s, n*d] layout.

    Saved residuals with a trailing head_dim < 128 pad 2x on the lane dim
    (TPU tiling T(8,128)); merging heads makes the save lane-aligned. The
    round-trip transposes are cheap relative to the HBM they free.
    """
    b, n, s, d = t.shape
    tf = checkpoint_name(t.transpose(0, 2, 1, 3).reshape(b, s, n * d), name)
    return tf.reshape(b, s, n, d).transpose(0, 2, 1, 3)


def _layer_body(x, lp, cfg: TransformerConfig, rope_tables, mesh, interpret,
                inner: Optional[InnerAxes] = None, active=None):
    """One transformer layer. ``active`` (a traced bool, pipeline gate mode
    "inner" only) wraps each matmul-heavy segment in ``_gated`` while the
    collectives — TP psums here, ring/Ulysses comms inside
    ``_inner_attention``, expert all-to-alls inside ``_moe_a2a_local`` —
    run unconditionally between the segments, so every device hits them in
    the same program order regardless of its tick predicate. checkpoint_name
    saves stay OUTSIDE the conds so remat policies see them in every mode."""
    b, s, h = x.shape
    ap, mp = lp["attn"], lp["mlp"]
    dt = cfg.dtype
    tp = inner is not None and inner.tp

    def qkv_fn(x):
        y = _norm(x, lp["attn_norm"], cfg)
        q = jnp.einsum("bsh,hnd->bnsd", y, ap["wq"].astype(dt))
        k = jnp.einsum("bsh,hnd->bnsd", y, ap["wk"].astype(dt))
        v = jnp.einsum("bsh,hnd->bnsd", y, ap["wv"].astype(dt))
        if cfg.use_bias:
            q = q + ap["bq"].astype(dt)[None, :, None, :]
            k = k + ap["bk"].astype(dt)[None, :, None, :]
            v = v + ap["bv"].astype(dt)[None, :, None, :]
        if cfg.pos == "rope":
            cos, sin = rope_tables
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        return q, k, v

    q, k, v = _gated(active, qkv_fn, x)
    q = _save_flat(q, "qkv")
    k = _save_flat(k, "qkv")
    v = _save_flat(v, "qkv")
    if inner is not None:
        o = _inner_attention(q, k, v, cfg, inner, interpret, active=active)
    else:
        o = _sharded_attention(q, k, v, cfg, mesh, interpret)
    # merge heads before the named save: [b, s, n*d] keeps the residual's
    # last dim lane-aligned (head_dim 64 in [b,n,s,d] pads 2x to 128 lanes —
    # a measured 700MB/layer-stack tax in the r4 seq-8192 OOM dumps)
    o = checkpoint_name(
        o.transpose(0, 2, 1, 3).reshape(b, s, -1), "attn_out"
    )
    o = _gated(active, lambda oo: jnp.einsum(
        "bse,eh->bsh", oo, ap["wo"].astype(dt).reshape(-1, h)), o)
    if tp:  # partial sum over the local head shard (unconditional)
        o = jax.lax.psum(o, "model")

    def resid_attn(x, o):
        if cfg.use_bias:
            o = o + ap["bo"].astype(dt)
        x = x + o
        return x, _norm(x, lp["mlp_norm"], cfg)

    x, y = _gated(active, resid_attn, x, o)
    if cfg.num_experts:
        out, aux = _moe_mlp(y, mp, cfg, mesh=mesh, inner=inner, active=active)
        if tp and cfg.moe_dispatch != "a2a":
            # a2a's shard_map psums its own model-partial projections
            out = jax.lax.psum(out, "model")
        return x + out, aux

    def mlp_fn(y):
        if cfg.act == "swiglu":
            hidden = swiglu(
                jnp.einsum("bsh,hm->bsm", y, mp["wi"].astype(dt)),
                jnp.einsum("bsh,hm->bsm", y, mp["wg"].astype(dt)),
            )
        else:
            hidden = jnp.einsum("bsh,hm->bsm", y, mp["wi"].astype(dt))
            if cfg.use_bias:
                hidden = hidden + mp["bi"].astype(dt)
            hidden = gelu(hidden)
        return jnp.einsum("bsm,mh->bsh", hidden, mp["wo"].astype(dt))

    out = _gated(active, mlp_fn, y)
    if tp:  # partial sum over the local mlp shard (unconditional)
        out = jax.lax.psum(out, "model")
    if cfg.use_bias:
        # gated, and AFTER the psum: the replicated bias must land once (a
        # pre-psum add would scale by the TP degree), and a bubble tick must
        # emit genuine zeros — previously the add sat outside the gate and
        # the schedule's never-consumed invariant was load-bearing by
        # accident (ADVICE r5)
        out = _gated(active, lambda o: o + mp["bo"].astype(dt), out)
    return x + out, jnp.zeros((2,), jnp.float32)


def _moe_mlp(y, mp, cfg: TransformerConfig, mesh=None,
             inner: "Optional[InnerAxes]" = None, active=None):
    """Top-k routed expert MLPs (see TransformerConfig.moe_dispatch).

    Router math in f32. Expert tensors carry a leading E dim which the
    `expert` mesh axis shards. Dispatch modes: "capacity" scatters globally
    and trusts XLA's lowering of the scatter/gather onto the mesh; "a2a"
    moves tokens with an explicit ``lax.all_to_all`` over the expert axis
    inside a shard_map (VERDICT r3 #6); "dense" computes every expert on
    every token (parity oracle).

    Returns ``(out, aux)`` with aux a 2-vector: [Switch load-balance loss,
    fraction of routed assignments dropped at expert capacity].
    """
    E, k = cfg.num_experts, min(cfg.expert_top_k, cfg.num_experts)

    def route_fn(y):
        logits = jnp.einsum("bsh,he->bse", y.astype(jnp.float32),
                            mp["router"].astype(jnp.float32))
        top_vals, top_idx = jax.lax.top_k(logits, k)          # [b,s,k]
        top_gates = jax.nn.softmax(top_vals, axis=-1)
        # Switch-style load balance: f_e = fraction of routed assignments on
        # expert e, P_e = mean router prob. aux = E * sum f_e P_e — equals
        # 1.0 at perfect balance, approaches E as routing collapses onto one
        # expert.
        probs = jax.nn.softmax(logits, axis=-1)               # [b,s,E]
        sel = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)   # [b,s,k,E]
        f = sel.sum(axis=2).mean(axis=(0, 1)) / k             # [E], sums to 1
        p_mean = probs.mean(axis=(0, 1))
        balance = (E * (f * p_mean).sum()).astype(jnp.float32)
        return top_idx, top_gates, balance

    top_idx, top_gates, balance = _gated(active, route_fn, y)
    if cfg.moe_dispatch == "dense":
        out = _gated(active, lambda yy, ti, tg: _moe_dense(
            yy, mp, cfg, ti, tg), y, top_idx, top_gates)
        drop = jnp.zeros((), jnp.float32)
    elif cfg.moe_dispatch == "capacity":
        out, drop = _gated(active, lambda yy, ti, tg: _moe_capacity(
            yy, mp, cfg, ti, tg), y, top_idx, top_gates)
    elif cfg.moe_dispatch == "a2a":
        out, drop = _moe_a2a(y, mp, cfg, top_idx, top_gates, mesh, inner,
                             active=active)
    else:
        raise ValueError(
            f"unknown moe_dispatch {cfg.moe_dispatch!r}; "
            f"valid: capacity|a2a|dense")
    return out, jnp.stack([balance, drop])


def _expert_ffn(xin, mp, cfg: TransformerConfig):
    """The expert MLP stack over [E, ..., h] inputs."""
    dt = cfg.dtype
    hi = jnp.einsum("e...h,ehm->e...m", xin, mp["wi"].astype(dt))
    if cfg.act == "swiglu":
        hg = jnp.einsum("e...h,ehm->e...m", xin, mp["wg"].astype(dt))
        inner = swiglu(hi, hg)
    else:
        inner = gelu(hi)
    return jnp.einsum("e...m,emh->e...h", inner, mp["wo"].astype(dt))


def _moe_dense(y, mp, cfg: TransformerConfig, top_idx, top_gates):
    dt = cfg.dtype
    logits_shape = (*top_idx.shape[:2], cfg.num_experts)
    gates = jnp.zeros(logits_shape, jnp.float32).at[     # [b,s,E]
        jnp.arange(top_idx.shape[0])[:, None, None],
        jnp.arange(top_idx.shape[1])[None, :, None],
        top_idx,
    ].set(top_gates)
    ye = _expert_ffn(
        jnp.broadcast_to(y[None], (cfg.num_experts, *y.shape)), mp, cfg)
    return jnp.einsum("ebsh,bse->bsh", ye, gates.astype(dt))


def _capacity_plan(top_idx, top_gates, E: int, k: int, cap: int):
    """Assign each (token, choice) routing assignment a slot within its
    expert's fixed [cap] buffer: returns (e, t, g, slot, keep, drop) — the
    per-assignment expert / token / gate arrays (token order), each kept
    assignment's slot, and the dropped-assignment fraction.

    Positions come from a cumsum over the one-hot expert selection, not an
    argsort+searchsorted group-by: TPU sorts are bitonic networks while the
    [T*k, E] cumsum is bandwidth-cheap — measured +4.6% end-to-end on the
    MoE-1B bench (MFU 0.288 -> 0.302). The cumsum runs in int32 — exact up
    to 2^31 assignments, comfortably past any GSPMD global token array,
    where an f32 count would saturate at 2^24 (ADVICE r4). Slot order
    within an expert is token order, the same order the stable sort
    produced."""
    T = top_idx.shape[0]
    flat_e = top_idx.reshape(T * k)                        # expert per assignment
    flat_g = top_gates.reshape(T * k).astype(jnp.float32)
    flat_t = jnp.repeat(jnp.arange(T), k)                  # token per assignment
    sel = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)       # [T*k, E]
    pos = (jnp.cumsum(sel, axis=0) * sel).sum(-1) - 1
    keep = pos < cap
    slot = jnp.where(keep, pos, 0)
    drop = 1.0 - keep.astype(jnp.float32).mean()
    return flat_e, flat_t, flat_g, slot, keep, drop


def _dispatch_tables(top_idx, top_gates, E: int, k: int, cap: int):
    """Gather-form dispatch plan (round 5, VERDICT r4 #2).

    The r4 dispatch scattered token rows into the [E, cap, h] buffer and
    scatter-added expert outputs back per assignment — and XLA lowers
    f32/bf16 scatters on TPU to serialized update loops. The dispatch
    relation is a bipartite matching with bounded degree on BOTH sides
    (k assignments per token, one token per slot), so with index tables in
    both directions every data movement — forward dispatch, forward
    combine, and both their transposes (_gather_dispatch/_gather_combine
    custom VJPs) — is a gather. The only scatters left are the int32/f32
    [E, cap+1] tables built here (~KBs). Empty slots point at the sentinel
    row T (the ops pad with a zero row); dropped assignments land in the
    discarded overflow column cap.

    Returns (token_for_slot [E, cap], slot [T, k], keep [T, k], drop).
    """
    T = top_idx.shape[0]
    ae, at_, _, slot, keep, drop = _capacity_plan(top_idx, top_gates, E, k, cap)
    tfs = jnp.full((E, cap + 1), T, jnp.int32)
    tfs = tfs.at[ae, jnp.where(keep, slot, cap)].set(at_)
    return tfs[:, :cap], slot.reshape(T, k), keep.reshape(T, k), drop


@jax.custom_vjp
def _gather_dispatch(x, tfs, top_idx, slot, keep):
    """xin[e, c] = x[tfs[e, c]] ([E, cap, h]; sentinel row T reads zeros).
    The custom transpose turns what autodiff would make a scatter-add over
    slots into a per-token gather: dx[t] = sum_j keep[t,j] *
    dxin[top_idx[t,j], slot[t,j]]."""
    xp = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)], axis=0)
    return xp[tfs]


def _gather_dispatch_fwd(x, tfs, top_idx, slot, keep):
    return _gather_dispatch(x, tfs, top_idx, slot, keep), (top_idx, slot, keep)


def _gather_dispatch_bwd(res, dxin):
    top_idx, slot, keep = res
    dx = jnp.einsum("tkh,tk->th", dxin[top_idx, slot],
                    keep.astype(dxin.dtype))
    return dx, None, None, None, None


_gather_dispatch.defvjp(_gather_dispatch_fwd, _gather_dispatch_bwd)


@jax.custom_vjp
def _gather_combine(ye, w, tfs, top_idx, slot, keep):
    """out[t] = sum_j w[t,j] * ye[top_idx[t,j], slot[t,j]] ([T, h]).
    ``w`` [T, k] f32 carries the gate weights (zero for dropped
    assignments) so router gradients flow. The custom transpose gathers in
    both directions: dye via the token-for-slot table, dw via the same
    [T, k, h] gather as the forward."""
    return jnp.einsum("tkh,tk->th", ye[top_idx, slot], w.astype(ye.dtype))


def _gather_combine_fwd(ye, w, tfs, top_idx, slot, keep):
    return _gather_combine(ye, w, tfs, top_idx, slot, keep), (
        ye, w, tfs, top_idx, slot, keep)


def _gather_combine_bwd(res, dout):
    ye, w, tfs, top_idx, slot, keep = res
    E, cap, h = ye.shape
    # per-slot gate weight (tiny f32 scatter; dropped -> overflow column)
    slot_w = jnp.where(keep, slot, cap)
    w_slot = jnp.zeros((E, cap + 1), jnp.float32).at[top_idx, slot_w].set(w)
    w_slot = w_slot[:, :cap]
    dout_pad = jnp.concatenate(
        [dout, jnp.zeros((1, h), dout.dtype)], axis=0)
    # stay in the activation dtype: an f32 [E, cap, h] intermediate would
    # spike HBM by 2x for no accuracy the fwd (bf16 multiply) ever had
    dye = (w_slot.astype(dout.dtype)[..., None] * dout_pad[tfs]
           ).astype(ye.dtype)
    dw = jnp.einsum("tkh,th->tk", ye[top_idx, slot].astype(jnp.float32),
                    dout.astype(jnp.float32))
    return dye, dw, None, None, None, None


_gather_combine.defvjp(_gather_combine_fwd, _gather_combine_bwd)


def _moe_capacity(y, mp, cfg: TransformerConfig, top_idx, top_gates):
    """Capacity dispatch: tokens group into each expert's fixed [cap, h]
    block, assignments past capacity are dropped (their combine weight is
    zero) — the standard GShard trade for static shapes. Both data
    movements are GATHERS from the int32 plan tables (_dispatch_tables):
    no [*, h]-width scatter anywhere. The gathers are global; XLA lowers
    them onto the expert mesh axis.

    With ``cfg.moe_cap_block`` > 0 the capacity dimension streams: the
    gather → expert-FFN → combine chain runs per cap-chunk inside a
    rematerialized ``lax.scan`` (_moe_capacity_streamed), so neither the
    [E, cap, h] dispatch buffers nor the [E, cap, mlp] FFN intermediates
    ever materialize whole — the round-5 measured HBM wall that blocked
    microbatch scaling (VERDICT r5 weak #2)."""
    dt = cfg.dtype
    b, s, h = y.shape
    E, k = cfg.num_experts, min(cfg.expert_top_k, cfg.num_experts)
    T = b * s
    cap = max(int(T * k / E * cfg.expert_capacity_factor), 1)

    x = y.reshape(T, h)
    ti, tg = top_idx.reshape(T, k), top_gates.reshape(T, k)
    tfs, slot, keep, drop = _dispatch_tables(ti, tg, E, k, cap)
    if cfg.moe_cap_block and cap > cfg.moe_cap_block:
        out = _moe_capacity_streamed(
            x, mp, cfg, tfs, ti, tg, slot, keep, cap, cfg.moe_cap_block)
    else:
        xin = _gather_dispatch(x, tfs, ti, slot, keep)     # [E, cap, h]
        ye = _expert_ffn(xin, mp, cfg)                     # [E, cap, h]
        w = tg.astype(jnp.float32) * keep.astype(jnp.float32)
        out = _gather_combine(ye, w, tfs, ti, slot, keep)  # [T, h]
    return out.astype(dt).reshape(b, s, h), drop


def _moe_capacity_streamed(x, mp, cfg, tfs, ti, tg, slot, keep, cap, cb):
    """Cap-blocked dispatch: scan chunks of ``cb`` expert slots, each chunk
    gathering its tokens, running the expert FFN, and combining into a
    running [T, h] accumulator. Per-chunk state is [E, cb, {h,mlp}] — cap/cb
    times smaller than the one-shot buffers — and ``jax.checkpoint`` on the
    body keeps the backward at the same bound (chunks recompute, only the
    carry is saved; the same trick lm_loss_from_hidden uses for the vocab).

    Semantics are identical to the one-shot path: each kept assignment's
    slot lands in exactly one chunk, the masked gate weight zeroes it
    everywhere else, and the custom-VJP gathers see per-chunk tables of the
    same form they see globally — so gradients decompose into per-chunk
    contributions that sum to the one-shot gradient (parity-tested).
    ``cap`` pads up to a cb multiple with sentinel slots (they gather the
    zero row and carry zero combine weight)."""
    T, h = x.shape
    E = tfs.shape[0]
    nc = -(-cap // cb)
    if nc * cb != cap:
        tfs = jnp.concatenate(
            [tfs, jnp.full((E, nc * cb - cap), T, jnp.int32)], axis=1)
    tfs_chunks = tfs.reshape(E, nc, cb).transpose(1, 0, 2)  # [nc, E, cb]

    def body(acc, inp):
        c, tfs_c = inp
        lo = c * cb
        in_chunk = keep & (slot >= lo) & (slot < lo + cb)
        slot_l = jnp.clip(slot - lo, 0, cb - 1)
        xin_c = _gather_dispatch(x, tfs_c, ti, slot_l, in_chunk)
        ye_c = _expert_ffn(xin_c, mp, cfg)                 # [E, cb, h]
        w_c = tg.astype(jnp.float32) * in_chunk.astype(jnp.float32)
        out_c = _gather_combine(ye_c, w_c, tfs_c, ti, slot_l, in_chunk)
        return acc + out_c.astype(acc.dtype), None

    body = jax.checkpoint(body, prevent_cse=False)
    acc0 = jnp.zeros((T, h), jnp.float32)
    out, _ = jax.lax.scan(body, acc0, (jnp.arange(nc), tfs_chunks))
    return out


def _moe_a2a_local(y, top_idx, top_gates, mp, cfg: TransformerConfig,
                   axis_name: Optional[str], ep_size: int,
                   model_axis: Optional[str] = None, active=None):
    """Device-local half of the explicit all-to-all dispatch (GShard
    layout, SURVEY.md:130). Runs inside a shard_map (or any manual-
    collective region): the local tokens' assignments scatter into per-
    expert send buffers [E, cap, h], one ``lax.all_to_all`` over the
    expert axis delivers each expert-owner its tokens, the local experts'
    FFN runs on [E_loc, ep*cap, h], and a reverse all_to_all returns
    outputs to their source for the gate-weighted combine. ``cap`` is per
    (source device, expert), so the buffers — and therefore the a2a
    payload — are static shapes.
    """
    dt = cfg.dtype
    b, s, h = y.shape
    E, k = cfg.num_experts, min(cfg.expert_top_k, cfg.num_experts)
    e_loc = E // ep_size
    T = b * s
    cap = max(int(T * k / E * cfg.expert_capacity_factor), 1)

    x = y.reshape(T, h)
    ti, tg = top_idx.reshape(T, k), top_gates.reshape(T, k)

    def dispatch_fn(x, ti, tg):
        tfs, slot, keep, drop = _dispatch_tables(ti, tg, E, k, cap)
        xin = _gather_dispatch(x, tfs, ti, slot, keep)     # [E, cap, h]
        return xin, tfs, slot, keep, drop

    # plan + gather-dispatch gated; the all_to_alls and the model psum run
    # unconditionally (on zero buffers during pipeline bubble ticks) so the
    # collective program order is identical on every device
    xin, tfs, slot, keep, drop = _gated(
        active, dispatch_fn, x, ti, tg)
    if ep_size > 1:
        # [ep, e_loc, cap, h]: peer p's block -> device p; received axis 0
        # indexes the source device
        recv = jax.lax.all_to_all(
            xin.reshape(ep_size, e_loc, cap, h), axis_name, 0, 0)
        xin_loc = recv.transpose(1, 0, 2, 3).reshape(e_loc, ep_size * cap, h)
    else:
        xin_loc = xin
    ye = _gated(active, lambda xi: _expert_ffn(xi, mp, cfg), xin_loc)
    if model_axis is not None:
        ye = jax.lax.psum(ye, model_axis)
    if ep_size > 1:
        back = jax.lax.all_to_all(
            ye.reshape(e_loc, ep_size, cap, h).transpose(1, 0, 2, 3),
            axis_name, 0, 0)                               # axis 0: owner
        ye = back.reshape(E, cap, h)

    def combine_fn(ye, tg):
        w = tg.astype(jnp.float32) * keep.astype(jnp.float32)
        return _gather_combine(ye, w, tfs, ti, slot, keep).astype(dt)

    out = _gated(active, combine_fn, ye, tg)
    return out.reshape(b, s, h), drop


def _moe_a2a(y, mp, cfg: TransformerConfig, top_idx, top_gates, mesh,
             inner: "Optional[InnerAxes]", active=None):
    """Dispatch wrapper for moe_dispatch="a2a".

    In jit-auto mode a shard_map over the full mesh runs the manual
    dispatch; inside a pipeline (already manual) the local core is called
    directly. Without a mesh (plain apply) it degenerates to the ep=1
    local path — identical math, no comms.
    """
    if inner is not None:
        # already inside a manual region (the pipeline's shard_map): run
        # the local core directly, with the expert comm axis when the mesh
        # shards experts
        ep = inner.ep_size
        return _moe_a2a_local(
            y, top_idx, top_gates, mp, cfg,
            "expert" if ep > 1 else None, ep,
            model_axis="model" if inner.tp else None, active=active)
    if mesh is None:
        return _moe_a2a_local(y, top_idx, top_gates, mp, cfg, None, 1)

    ep = mesh.shape["expert"]
    if cfg.num_experts % ep:
        raise ValueError(
            f"num_experts {cfg.num_experts} not divisible by expert mesh "
            f"axis {ep}")
    tp = mesh.shape["model"] > 1
    tok_spec = P(("data", "fsdp", "expert"), "context", None)
    idx_spec = P(("data", "fsdp", "expert"), "context", None)
    w_specs = {
        "wi": P("expert", None, "model"),
        "wo": P("expert", "model", None),
    }
    if "wg" in mp:
        w_specs["wg"] = P("expert", None, "model")
    experts = {name: mp[name] for name in w_specs}

    @functools.partial(
        shard_map, mesh=mesh, check_vma=False,
        in_specs=(tok_spec, idx_spec, idx_spec,
                  {n: w_specs[n] for n in experts}),
        out_specs=(tok_spec, P()),
    )
    def _disp(y_l, idx_l, gates_l, mp_l):
        out, drop = _moe_a2a_local(
            y_l, idx_l, gates_l, mp_l, cfg, "expert", ep,
            model_axis="model" if tp else None)
        drop = jax.lax.pmean(drop, ("data", "fsdp", "expert", "context"))
        return out, drop

    return _disp(y, top_idx, top_gates, experts)


def run_trunk(x, layer_params, cfg: TransformerConfig, rope_tables, mesh, interpret):
    """Scan the stacked layers over x with the configured remat policy
    (shared by apply() and encoder-only models like ViT). With a ``stage``
    mesh axis >1 the trunk runs as a GPipe pipeline instead: layers shard
    over stages, activations rotate via ppermute (parallel/pipeline.py)."""
    if mesh is not None and mesh.shape.get("stage", 1) > 1:
        from ..parallel.pipeline import gpipe_trunk

        ep_size = mesh.shape["expert"]
        if cfg.num_experts and ep_size > 1:
            if cfg.moe_dispatch != "a2a":
                raise ValueError(
                    f"pipeline with expert={ep_size} needs moe_dispatch="
                    f"'a2a': {cfg.moe_dispatch!r} dispatch assumes every "
                    f"expert is device-local, but each stage shard holds "
                    f"only num_experts/{ep_size} of them"
                )
            if cfg.num_experts % ep_size:
                raise ValueError(
                    f"num_experts {cfg.num_experts} not divisible by expert "
                    f"mesh axis {ep_size}")
        inner = InnerAxes(
            tp=mesh.shape["model"] > 1, cp=mesh.shape["context"] > 1,
            ep_size=ep_size)
        # params enter the pipeline shard_map sharded over stage (layer dim)
        # and model (TP dims); fsdp-sharded storage all-gathers at entry —
        # the same gather FSDP pays anyway, hoisted once per step.
        rules = ShardingRules().override(layers="stage", embed=None, vocab=None)
        pspec = param_specs(cfg, rules)["layers"]

        def pp_body(xl, lp, act=None):
            tables = rope_tables
            if inner.cp and tables is not None:
                # each context shard rotates with its *global* positions
                c = jax.lax.axis_index("context")
                sl = xl.shape[1]
                tables = tuple(
                    jax.lax.dynamic_slice_in_dim(t, c * sl, sl, 0)
                    for t in tables)
            return _scan_layers(xl, lp, cfg, tables, None, interpret,
                                inner=inner, active=act)

        # bodies with collectives (TP psums / ring ppermutes / expert
        # all-to-alls) gate their matmul segments around unconditionally-
        # executed collectives (gate="inner"); collective-free bodies sit
        # under one whole-body cond (gate="full"). Either way bubble ticks
        # skip the stage's FLOPs — VERDICT r4 #1.
        # (the expert a2a only exists in MoE layers — dense models on an
        # expert-axis mesh still take the whole-body gate)
        has_collectives = (inner.tp or inner.cp
                           or bool(cfg.num_experts and inner.ep_size > 1))
        gate = cfg.pp_gate
        if gate == "auto":
            gate = "inner" if has_collectives else "full"
        elif gate == "full" and has_collectives:
            raise ValueError(
                "pp_gate='full' is unsound for stage bodies with "
                "collectives (TP/CP/EP) — use 'auto', 'inner', or 'none'")
        return gpipe_trunk(
            x, layer_params, pp_body, mesh,
            num_microbatches=cfg.pp_microbatches, param_spec=pspec,
            gate=gate, remat_ticks=cfg.pp_remat_ticks)
    return _scan_layers(x, layer_params, cfg, rope_tables, mesh, interpret)


def _scan_layers(x, layer_params, cfg: TransformerConfig, rope_tables, mesh,
                 interpret, inner: Optional[InnerAxes] = None, active=None):
    def body(x, lp):
        new_x, aux = _layer_body(x, lp, cfg, rope_tables, mesh, interpret,
                                 inner, active)
        return new_x, aux
    if cfg.remat == "full":
        body = jax.checkpoint(body, prevent_cse=False)
    elif cfg.remat == "attn":
        # Save only the attention outputs: the one tensor whose recompute
        # re-runs the flash kernel (its bwd already recomputes scores);
        # projections/MLP recompute as single MXU matmuls. HBM cost over
        # "full" is just [B,S,H] per layer; recompute cost drops by the whole
        # attention pass. The winning policy for ~1B on one 16 GiB chip.
        body = jax.checkpoint(
            body, prevent_cse=False,
            policy=jax.checkpoint_policies.save_only_these_names("attn_out"),
        )
    elif cfg.remat == "attn_qkv":
        # Also keep post-RoPE q/k/v: +[B,S,(heads+2*kv)*hd] per layer buys
        # the backward out of recomputing the qkv projections + rope (cheap
        # with GQA: kv is heads/8 of q).
        body = jax.checkpoint(
            body, prevent_cse=False,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "qkv"),
        )
    elif cfg.remat == "dots":
        body = jax.checkpoint(
            body, prevent_cse=False,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    elif cfg.remat != "none":
        raise ValueError(
            f"unknown remat policy {cfg.remat!r}; "
            f"valid: none|full|attn|attn_qkv|dots"
        )
    x, aux = jax.lax.scan(body, x, layer_params)
    return x, aux.mean(axis=0)  # [L, 2] -> mean over layers


def apply_hidden(
    params: dict,
    tokens: jax.Array,
    cfg: TransformerConfig,
    *,
    mesh: Optional[Mesh] = None,
    interpret: Optional[bool] = None,
    inputs_embeds: Optional[jax.Array] = None,
    return_aux: bool = False,
) -> jax.Array:
    """Trunk forward: tokens [batch, seq] -> final-norm hidden states
    [batch, seq, hidden] (activation dtype). The vocab projection is left to
    the caller — the training loss fuses it blockwise (lm_loss_from_hidden)
    so the full [B,S,V] f32 logits tensor never materializes.

    ``inputs_embeds`` bypasses token embedding (ViT patches, BERT pipelines).
    """
    dt = cfg.dtype
    if inputs_embeds is None:
        x = params["embed"]["tokens"].astype(dt)[tokens]
    else:
        x = inputs_embeds.astype(dt)
    s = x.shape[1]
    if cfg.pos == "learned":
        x = x + params["embed"]["pos"].astype(dt)[None, :s]
    rope_tables = None
    if cfg.pos == "rope":
        if s > cfg.max_seq:
            raise ValueError(
                f"sequence length {s} exceeds max_seq {cfg.max_seq}: RoPE "
                f"positions would silently clamp"
            )
        cos, sin = rope_frequencies(cfg.hd, cfg.max_seq, cfg.rope_theta)
        rope_tables = (cos[:s], sin[:s])

    x, aux = run_trunk(x, params["layers"], cfg, rope_tables, mesh, interpret)
    hidden = _norm(x, params["final_norm"], cfg)
    if return_aux:
        return hidden, aux
    return hidden


def head_weights(params: dict, cfg: TransformerConfig) -> tuple[jax.Array, bool]:
    """LM-head weight and its orientation: (w, vocab_major). vocab_major
    means w is [vocab, hidden] (tied embeddings) vs [hidden, vocab]."""
    if cfg.tie_embeddings:
        return params["embed"]["tokens"], True
    return params["lm_head"]["w"], False


def apply(
    params: dict,
    tokens: jax.Array,
    cfg: TransformerConfig,
    *,
    mesh: Optional[Mesh] = None,
    interpret: Optional[bool] = None,
    inputs_embeds: Optional[jax.Array] = None,
) -> jax.Array:
    """Full forward: tokens [batch, seq] -> logits [batch, seq, vocab] (f32).

    Evaluation/inference path; training uses apply_hidden +
    lm_loss_from_hidden to avoid materializing the logits.
    """
    x = apply_hidden(
        params, tokens, cfg, mesh=mesh, interpret=interpret,
        inputs_embeds=inputs_embeds,
    )
    w, vocab_major = head_weights(params, cfg)
    eq = "bsh,vh->bsv" if vocab_major else "bsh,hv->bsv"
    logits = jnp.einsum(eq, x, w.astype(cfg.dtype))
    return logits.astype(jnp.float32)


def cross_entropy_loss(
    logits: jax.Array, labels: jax.Array, mask: Optional[jax.Array] = None
) -> jax.Array:
    """Mean token cross entropy in f32; mask=0 positions excluded."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is None:
        return nll.mean()
    mask = mask.astype(jnp.float32)
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def _chunk_nll(x, w, labels, vocab_major):
    """Per-token NLL for one chunk: project to vocab (bf16 matmul, MXU),
    reduce in f32. The chunk's logits are the only vocab-sized live tensor."""
    eq = "...h,vh->...v" if vocab_major else "...h,hv->...v"
    logits = jnp.einsum(eq, x, w.astype(x.dtype)).astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return logz - gold


def lm_loss_from_hidden(
    x: jax.Array,
    w: jax.Array,
    labels: jax.Array,
    mask: Optional[jax.Array] = None,
    *,
    vocab_major: bool = False,
    chunk_tokens: int = 4096,
) -> jax.Array:
    """Blockwise fused vocab-projection + cross entropy.

    Scans sequence chunks of ``x`` [batch, seq, hidden] against the head
    weight so at most ~chunk_tokens × vocab f32 logits are live at once
    (vs batch × seq × vocab for the unfused path — 4 GB at batch 16,
    seq 2048, vocab 32k). The chunk body is rematerialized in the backward
    pass, so the same bound holds for gradients. Numerics match
    cross_entropy_loss(apply(...)) exactly: identical matmul dtype and f32
    reductions, summed over the same token set.
    """
    b, s, h = x.shape
    mask_f = None if mask is None else mask.astype(jnp.float32)
    nc = 1
    if chunk_tokens and b * s > chunk_tokens:
        # smallest chunk count that divides seq and fits the token budget
        nc = next(
            (c for c in range(1, s + 1) if s % c == 0 and (s // c) * b <= chunk_tokens),
            s,
        )
    if nc == 1:
        nll = _chunk_nll(x, w, labels, vocab_major)
        if mask_f is None:
            return nll.mean()
        return (nll * mask_f).sum() / jnp.maximum(mask_f.sum(), 1.0)

    cs = s // nc
    xs = x.reshape(b, nc, cs, h).swapaxes(0, 1)
    ls = labels.reshape(b, nc, cs).swapaxes(0, 1)
    if mask_f is None:
        ms = jnp.ones((nc, b, cs), jnp.float32)
    else:
        ms = mask_f.reshape(b, nc, cs).swapaxes(0, 1)

    def body(carry, inp):
        xc, lc, mc = inp
        nll = _chunk_nll(xc, w, lc, vocab_major)
        return (carry[0] + (nll * mc).sum(), carry[1] + mc.sum()), None

    body = jax.checkpoint(body, prevent_cse=False)
    zero = jnp.zeros((), jnp.float32)
    (total, count), _ = jax.lax.scan(body, (zero, zero), (xs, ls, ms))
    return total / jnp.maximum(count, 1.0)
