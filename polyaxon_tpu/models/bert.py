"""BERT-base MLM (BASELINE config 3: multi-worker BERT-base pretraining).

Bidirectional encoder on the shared core (causal=False); masked-LM loss
masks out non-[MASK] positions via the loss mask."""

from __future__ import annotations

from dataclasses import replace
from typing import Optional

import jax
import jax.numpy as jnp

from .transformer import TransformerConfig, cross_entropy_loss

BERT_BASE = TransformerConfig(
    vocab_size=30522, hidden=768, num_layers=12, num_heads=12, mlp_dim=3072,
    max_seq=512, norm="ln", act="gelu", pos="learned", causal=False,
    use_bias=True, tie_embeddings=True, eps=1e-12, dtype=jnp.bfloat16,
)

BERT_LARGE = replace(BERT_BASE, hidden=1024, num_layers=24, num_heads=16, mlp_dim=4096)

BERT_TINY = replace(
    BERT_BASE, vocab_size=256, hidden=64, num_layers=2, num_heads=4,
    mlp_dim=128, max_seq=128, dtype=jnp.float32, attn_impl="dense",
)

CONFIGS = {"bert-base": BERT_BASE, "bert-large": BERT_LARGE, "bert-tiny": BERT_TINY}

MASK_TOKEN_ID = 103  # [MASK] in the BERT WordPiece vocab


def mlm_mask_tokens(
    key: jax.Array, tokens: jax.Array, vocab_size: int, mask_rate: float = 0.15,
    mask_token_id: int = MASK_TOKEN_ID,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """BERT 80/10/10 masking. Returns (inputs, labels, loss_mask)."""
    k1, k2, k3 = jax.random.split(key, 3)
    selected = jax.random.uniform(k1, tokens.shape) < mask_rate
    roll = jax.random.uniform(k2, tokens.shape)
    random_tokens = jax.random.randint(k3, tokens.shape, 0, vocab_size)
    inputs = jnp.where(selected & (roll < 0.8), mask_token_id, tokens)
    inputs = jnp.where(selected & (roll >= 0.8) & (roll < 0.9), random_tokens, inputs)
    return inputs, tokens, selected


def mlm_loss(logits: jax.Array, labels: jax.Array, loss_mask: jax.Array) -> jax.Array:
    return cross_entropy_loss(logits, labels, loss_mask)
