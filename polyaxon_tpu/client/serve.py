"""Serve front: request-path failover over a service run's replicas
(ISSUE 12).

The control plane already survives replica churn; this is the piece that
makes a single *request* survive it. One :class:`ServeFront` holds an
ordered endpoint list (static, or a discovery callable refreshed per
attempt — e.g. the ``serve-endpoint-*.json`` files replicas publish into
the run dir) and applies the BaseClient failover doctrine to the
``/generate`` path:

- requests with a common prompt prefix **prefer the same replica**
  (prefix-affinity, ISSUE 17): the first ``affinity_block`` prompt
  tokens hash to a home replica, so each replica's radix prefix cache
  accumulates HOT prefixes instead of every replica holding a lukewarm
  copy of all of them. Affinity is a preference, not a pin — a dead,
  draining, or overloaded home replica falls back to the rotation
  below, trading a one-off re-prefill for availability;
- requests without usable affinity **round-robin across replicas** (a
  front that pins one replica starves the rest and melts under its own
  hot spot);
  **connect failures and 503s retry elsewhere** — a dead pod or a
  draining replica is a host-level verdict, the endpoint is skipped for
  ``dead_for_s`` before re-probing, and the request carries an
  idempotency id so the retry can never generate twice on one replica
  (the engine's completed-request cache answers).
- **429s back off** by the server's Retry-After (overload is
  service-wide: rotating doesn't help, waiting does) and count against
  the attempt budget.
- **a partially-streamed body is NEVER blindly re-POSTed**: a
  mid-stream disconnect resumes by id (``GET /result/{request_id}``) —
  the finished result comes from the completed-request cache of
  whichever replica ran it; only when no replica knows the id (the
  owner died before finishing) is the request re-submitted, which is
  safe exactly because it never completed anywhere.

Retries feed ``polyaxon_serve_request_retries_total`` in the front's OWN
registry; to land them on the control plane's pane of glass wire
``on_retry=store.count_serve_retries`` — do NOT pass the store's
registry as ``metrics``: the store already registered that family with a
``value_fn`` over its stats dict, which would shadow the front's
increments at scrape time.
"""

from __future__ import annotations

import json
import time
import uuid as _uuid
from typing import Any, Callable, Optional

import requests


class ServeUnavailableError(RuntimeError):
    """No replica accepted the request within the attempt budget."""


class _HostLevel(Exception):
    """Internal: a pre-body 503 on the stream path — retry elsewhere."""


class _Rejected(Exception):
    """Internal: a pre-body 429 on the stream path — back off, retry."""

    def __init__(self, retry_after):
        super().__init__("overloaded")
        self.retry_after = retry_after


class ServeFront:
    def __init__(
        self,
        endpoints: Optional[list] = None,
        endpoints_fn: Optional[Callable[[], list]] = None,
        *,
        timeout: float = 60.0,
        max_attempts: int = 8,
        backoff_s: float = 0.2,
        retry_after_cap_s: float = 10.0,
        metrics=None,
        on_retry: Optional[Callable[[int], None]] = None,
        affinity_block: int = 16,
    ):
        if not endpoints and endpoints_fn is None:
            raise ValueError("ServeFront needs endpoints or endpoints_fn")
        self._static = [e.rstrip("/") for e in (endpoints or [])]
        self._endpoints_fn = endpoints_fn
        self.timeout = float(timeout)
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.retry_after_cap_s = float(retry_after_cap_s)
        #: seconds a replica that answered with a host-level failure
        #: (connect error / 503) is skipped before being re-probed
        self.dead_for_s = 2.0
        #: prompt tokens hashed into the prefix-affinity key (0 disables
        #: affinity routing; match the replicas' serve block_size so one
        #: cached block's worth of prefix decides the home replica)
        self.affinity_block = int(affinity_block)
        self._rr = 0                      # round-robin start cursor
        self._dead: dict = {}             # endpoint -> monotonic re-probe time
        self._session = requests.Session()
        self.on_retry = on_retry
        from ..obs.metrics import MetricsRegistry

        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._c_retries = self.metrics.counter(
            "polyaxon_serve_request_retries_total",
            "Generate requests retried against another replica by the "
            "serve front (connect failures / 503s)")
        #: audit: every 429's Retry-After header value (None = missing —
        #: a contract violation the fault soak asserts never happens)
        self.rejections: list = []

    # -- endpoint rotation ---------------------------------------------------

    def _endpoints(self) -> list:
        eps = self._static
        if self._endpoints_fn is not None:
            try:
                eps = [e.rstrip("/") for e in self._endpoints_fn()] or eps
            except Exception:
                pass
        return eps or self._static

    def _affinity_key(self, body: dict) -> Optional[int]:
        """Stable hash of the first ``affinity_block`` prompt tokens (or
        prompt-string bytes) — requests sharing that much prefix share a
        home replica, so its radix cache sees the repeats."""
        if self.affinity_block <= 0:
            return None
        import zlib

        toks = body.get("tokens")
        if toks is not None:
            head = ",".join(str(int(t)) for t in
                            toks[:self.affinity_block]).encode()
        else:
            prompt = body.get("prompt")
            if not prompt:
                return None
            head = str(prompt)[:self.affinity_block * 8].encode(
                "utf-8", "replace")
        return zlib.crc32(head)

    def _pick(self, affinity: Optional[int] = None,
              first_attempt: bool = False) -> Optional[str]:
        """Pick a replica: on the FIRST attempt of a request with an
        affinity key, prefer its home replica (``key % len``) when not
        recently dead — the radix caches only warm up if repeats land on
        the same pod. Otherwise (no key, retries, dead home) round-robin
        across replicas (spread, not sticky-to-one), skipping endpoints
        recently seen host-level dead — unless every endpoint is marked
        dead, in which case probe anyway. None when discovery found
        nothing (the caller backs off and re-discovers next attempt)."""
        eps = self._endpoints()
        if not eps:
            return None
        now = time.monotonic()
        if affinity is not None and first_attempt:
            home = eps[affinity % len(eps)]
            if self._dead.get(home, 0) <= now:
                return home
        for _ in range(len(eps)):
            ep = eps[self._rr % len(eps)]
            self._rr += 1
            if self._dead.get(ep, 0) <= now:
                return ep
        return eps[self._rr % len(eps)]

    def _mark_dead(self, ep: str) -> None:
        self._dead[ep] = time.monotonic() + self.dead_for_s

    def _count_retry(self) -> None:
        self._c_retries.inc()
        if self.on_retry is not None:
            try:
                self.on_retry(1)
            except Exception:
                pass

    # -- the request path ----------------------------------------------------

    def generate(self, prompt: Optional[str] = None,
                 tokens: Optional[list] = None,
                 request_id: Optional[str] = None,
                 stream: bool = False,
                 deadline_s: Optional[float] = None,
                 **sampling: Any) -> dict:
        """One exactly-once generate against the replica fleet; returns
        the final result dict (with ``request_id``). Raises
        :class:`ServeUnavailableError` after the attempt budget."""
        rid = request_id or _uuid.uuid4().hex
        body: dict = {"request_id": rid, **sampling}
        if tokens is not None:
            body["tokens"] = list(tokens)
        else:
            body["prompt"] = prompt
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        if stream:
            body["stream"] = True
        last: Optional[BaseException] = None
        affinity = self._affinity_key(body)
        for attempt in range(self.max_attempts):
            ep = self._pick(affinity, first_attempt=(attempt == 0))
            if ep is None:
                # discovery found nothing (replicas not published yet):
                # back off and re-discover on the next attempt
                last = ServeUnavailableError("no endpoints discovered")
                time.sleep(min(self.backoff_s * (2 ** min(attempt, 4)),
                               2.0))
                continue
            try:
                if stream:
                    return self._generate_stream(ep, body, rid)
                r = self._session.post(f"{ep}/generate", json=body,
                                       timeout=self.timeout)
            except (requests.ConnectionError, requests.Timeout) as e:
                # host-level: dead/wedged replica. The id makes the
                # retry idempotent; nothing was delivered.
                last = e
                self._retry_elsewhere(ep, attempt)
                continue
            except _HostLevel as e:
                last = ServeUnavailableError(str(e))
                self._retry_elsewhere(ep, attempt)
                continue
            except _Rejected as e:
                last = ServeUnavailableError("overloaded")
                self._sleep_retry_after(e.retry_after)
                continue
            if r.status_code == 503:
                # draining / not-ready: explicit "route elsewhere"
                last = ServeUnavailableError(r.text[:200])
                self._retry_elsewhere(ep, attempt)
                continue
            if r.status_code == 429:
                # overload is service-wide: wait the server's hint, do
                # NOT mark the replica dead (it is serving, just full)
                last = ServeUnavailableError(f"overloaded: {r.text[:200]}")
                self._sleep_retry_after(r.headers.get("Retry-After"))
                continue
            r.raise_for_status()
            out = r.json()
            out.setdefault("request_id", rid)
            return out
        raise ServeUnavailableError(
            f"no replica served request {rid} in "
            f"{self.max_attempts} attempts") from last

    def _sleep_retry_after(self, ra) -> None:
        self.rejections.append(ra)
        try:
            wait = min(float(ra), self.retry_after_cap_s)
        except (TypeError, ValueError):
            wait = self.backoff_s
        time.sleep(wait)

    def _retry_elsewhere(self, ep: str, attempt: int) -> None:
        self._mark_dead(ep)
        self._count_retry()
        time.sleep(min(self.backoff_s * (2 ** min(attempt, 4)), 2.0))

    def _generate_stream(self, ep: str, body: dict, rid: str) -> dict:
        """NDJSON streaming with the no-blind-re-POST rule: a disconnect
        mid-body resumes by id instead of re-submitting. Pre-body 503s
        and 429s surface as the internal retry signals (nothing was
        streamed, so the non-stream failover rules apply unchanged)."""
        started = False
        try:
            r = self._session.post(f"{ep}/generate", json=body,
                                   timeout=self.timeout, stream=True)
            if r.status_code == 503:
                raise _HostLevel(r.text[:200])
            if r.status_code == 429:
                raise _Rejected(r.headers.get("Retry-After"))
            r.raise_for_status()
            final = None
            for line in r.iter_lines():
                if not line:
                    continue
                started = True
                final = json.loads(line)
            if final is not None and final.get("done"):
                final.setdefault("request_id", rid)
                return final
            raise requests.ConnectionError("stream ended without a result")
        except (requests.ConnectionError, requests.Timeout,
                requests.exceptions.ChunkedEncodingError) as e:
            if not started:
                raise
            # partial body: NEVER re-POST — resume by id
            self._count_retry()
            result = self.resume(rid)
            if result is not None:
                return result
            raise ServeUnavailableError(
                f"stream for {rid} broke and no replica holds its "
                "result") from e

    def resume(self, request_id: str,
               poll_timeout_s: float = 30.0) -> Optional[dict]:
        """Resume-by-id across the fleet: poll ``/result/{id}`` on every
        replica until one returns the finished result (202 = still
        generating → keep polling the owner). None when no replica knows
        the id."""
        deadline = time.monotonic() + poll_timeout_s
        while time.monotonic() < deadline:
            in_flight = False
            for ep in self._endpoints():
                try:
                    r = self._session.get(f"{ep}/result/{request_id}",
                                          timeout=self.timeout)
                except (requests.ConnectionError, requests.Timeout):
                    continue
                if r.status_code == 200:
                    out = r.json()
                    out.setdefault("request_id", request_id)
                    return out
                if r.status_code == 202:
                    in_flight = True
            if not in_flight:
                return None
            time.sleep(0.1)
        return None


def federated_endpoints(store, project: str,
                        uuids: Optional[list] = None,
                        name: Optional[str] = None) -> Callable[[], list]:
    """An ``endpoints_fn`` that discovers a service's replicas ACROSS
    clusters (ISSUE 16): every live service run of ``project`` — all of
    them, or just ``uuids``/``name``-matched ones — contributes the
    agent-stamped ``meta.service`` endpoint of whichever cluster hosts
    it. Pin one service run per cluster (``placement.cluster``) and a
    ServeFront over this callable keeps answering through the loss of an
    entire cluster: the lost cluster's endpoint goes connect-dead (the
    front rotates off it within one attempt), and the run itself is
    either already re-placed by failover or still serving from its pin's
    surviving siblings. Re-polled per request batch, so endpoints follow
    placement with no client restart."""
    def _endpoints() -> list:
        eps = []
        try:
            runs = store.list_runs(project=project)
        except Exception:
            return eps
        for run in runs:
            if uuids is not None and run["uuid"] not in uuids:
                continue
            if name is not None and run.get("name") != name:
                continue
            if run["status"] not in ("scheduled", "starting", "running"):
                continue
            svc = (run.get("meta") or {}).get("service")
            if not svc:
                continue
            eps.append(f"http://{svc['host']}:{svc['port']}")
        return eps
    return _endpoints
