"""HTTP clients for the API (upstream ``RunClient``/``ProjectClient``,
SURVEY.md §2 "Client" row — hand-written against our REST surface instead
of OpenAPI-generated)."""

from __future__ import annotations

import os
import time
from typing import Any, Optional

import requests

from ..resilience.retry import DEFAULT_HTTP_RETRY, RetryPolicy
from ..schemas.operation import V1Operation
from ..schemas.statuses import V1Statuses, is_done


def _iter_sse(resp, stop=None):
    """Parse a streaming SSE response into event dicts
    ``{"type", "id", "data"}`` (data JSON-decoded when possible).
    Comment lines (``:``) are keepalives; ``stop`` is checked at every
    line so a consumer can end the watch at a ping boundary."""
    import json as _json

    ev_type, ev_id, data_lines = None, None, []
    for raw in resp.iter_lines(decode_unicode=True):
        if stop is not None and stop.is_set():
            return
        if raw is None:
            continue
        line = raw if isinstance(raw, str) else raw.decode("utf-8")
        if line == "":
            if data_lines or ev_type:
                data = "\n".join(data_lines)
                try:
                    data = _json.loads(data) if data else {}
                except ValueError:
                    data = {"raw": data}
                yield {"type": ev_type or "message", "id": ev_id,
                       "data": data}
            ev_type, ev_id, data_lines = None, None, []
            continue
        if line.startswith(":"):
            continue
        field, _, value = line.partition(":")
        value = value[1:] if value.startswith(" ") else value
        if field == "event":
            ev_type = value
        elif field == "id":
            ev_id = value
        elif field == "data":
            data_lines.append(value)
        # "retry:" is honored by browsers; python consumers ignore it


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(f"API error {status}: {message}")
        self.status = status
        self.retry_after = retry_after


class BaseClient:
    """``host`` accepts ONE endpoint or an ordered failover list (a
    python list, or one comma-separated string — the env-var-friendly
    form pods receive via PLX_API_HOST): the client talks to the current
    endpoint and rotates to the next on a host-level failure — connection
    refused/reset, or a 503 (a demoted standby / degraded store answers
    503 on writes by contract). Sticky: after a failover every later call
    starts at the endpoint that worked. Fencing 409s and epoch 410s NEVER
    rotate or retry — they are verdicts about the caller, identical on
    every replica (ISSUE 7)."""

    def __init__(self, host="http://127.0.0.1:8000", timeout: float = 30.0,
                 auth_token: Optional[str] = None,
                 retry: Optional[RetryPolicy] = None):
        if isinstance(host, str):
            hosts = [h for h in (p.strip() for p in host.split(",")) if h]
        else:
            hosts = [str(h).strip() for h in host]
        self.hosts = [h.rstrip("/") for h in hosts]
        if not self.hosts:
            raise ValueError("client needs at least one API endpoint")
        self._host_idx = 0
        self.timeout = timeout
        # transient 5xx/429/connection failures are retried within a bounded
        # budget (VERDICT r5 Missing #3: no retry policy at all); a policy
        # with max_attempts=1 disables
        self.retry = retry if retry is not None else DEFAULT_HTTP_RETRY
        self._session = requests.Session()
        token = auth_token if auth_token is not None \
            else os.environ.get("PLX_AUTH_TOKEN")
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"

    @property
    def host(self) -> str:
        """The endpoint currently in use."""
        return self.hosts[self._host_idx]

    def _req(self, method: str, path: str, **kwargs: Any):
        if method.upper() in ("GET", "HEAD"):
            return self.retry.call(self._req_sweep, method, path, **kwargs)
        # Mutating verbs: an error AFTER the request was sent is ambiguous —
        # the server may have committed (a re-POST of create/restart would
        # duplicate the run). Retry only failures that are provably
        # pre-commit: an HTTP error response (our handlers raise before or
        # atomically with their write; injected 5xx/429 never reach one) or
        # a connect-phase failure (nothing was sent).
        return self.retry.call(self._req_sweep, method, path,
                               classify=self._mutation_retryable, **kwargs)

    def _mutation_retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, ApiError):
            return self.retry.is_retryable(exc)
        if isinstance(exc, (requests.exceptions.ConnectTimeout,
                            requests.exceptions.ConnectionError)) and \
                not isinstance(exc, requests.exceptions.ReadTimeout):
            return True
        return False

    def _rotate_on(self, method: str, exc: BaseException) -> bool:
        """Should this failure try the NEXT endpoint (same sweep, no
        backoff burned)? Only host-level failures rotate: the host is
        down (connection-phase error) or explicitly not serving (503 —
        demoted standby / degraded store). Any other HTTP answer means
        the host IS serving and every replica would answer the same —
        especially the terminal 409/410 verdicts. Mutations additionally
        require the failure to be provably pre-commit (the same rule as
        retrying them)."""
        status = getattr(exc, "status", None)
        if status is not None:
            host_level = status == 503
        else:
            host_level = isinstance(
                exc, (requests.exceptions.ConnectTimeout,
                      requests.exceptions.ConnectionError,
                      ConnectionError)) and not isinstance(
                exc, requests.exceptions.ReadTimeout)
        if not host_level:
            return False
        if method.upper() in ("GET", "HEAD"):
            return True
        return self._mutation_retryable(exc)

    def _req_sweep(self, method: str, path: str, **kwargs: Any):
        """One attempt = one sweep across the endpoint list starting at
        the current one. A sweep that fails everywhere surfaces the last
        error to the RetryPolicy (which then backs off and re-sweeps)."""
        last: Optional[BaseException] = None
        for _ in range(len(self.hosts)):
            try:
                return self._req_once(method, path, **kwargs)
            except BaseException as e:
                last = e
                if len(self.hosts) > 1 and self._rotate_on(method, e):
                    self._host_idx = (self._host_idx + 1) % len(self.hosts)
                    continue
                raise
        raise last

    def _req_once(self, method: str, path: str, **kwargs: Any):
        url = f"{self.host}{path}"
        resp = self._session.request(method, url, timeout=self.timeout, **kwargs)
        if resp.status_code >= 400:
            from ..resilience.retry import parse_retry_after

            raise ApiError(resp.status_code, resp.text[:500],
                           retry_after=parse_retry_after(resp.headers))
        return resp

    def _json(self, method: str, path: str, **kwargs: Any):
        return self._req(method, path, **kwargs).json()


class ProjectClient(BaseClient):
    def create(self, name: str, description: Optional[str] = None) -> dict:
        return self._json("POST", "/api/v1/projects",
                          json={"name": name, "description": description})

    def get(self, name: str) -> dict:
        return self._json("GET", f"/api/v1/projects/{name}")

    def list(self) -> list[dict]:
        return self._json("GET", "/api/v1/projects")


class AgentClient(BaseClient):
    """Control-plane observability: who holds the scheduler lease."""

    def lease(self, name: str = "scheduler") -> Optional[dict]:
        """The live agent lease row ({holder, token, ttl, renewed_at,
        expired}), or None when no agent has ever acquired (or the last
        one released on drain). ``expired: true`` means the holder stopped
        renewing — a successor may take over at any moment."""
        return self._json("GET", "/api/v1/agent/lease",
                          params={"name": name}).get("lease")

    def stats(self) -> dict:
        """The JSON twin of /metrics: {store: counters, metrics: snapshot
        with exact histogram p50/p95, lease: scheduler lease row} —
        `polyaxon status` and dashboards read this (docs/OBSERVABILITY.md)."""
        return self._json("GET", "/api/v1/stats")

    def prometheus(self) -> str:
        """The raw Prometheus text exposition (GET /metrics) — what a
        scraper sees; obs.parse_prometheus() parses it back."""
        return self._req("GET", "/metrics").text


class QuotaClient(BaseClient):
    """Tenant chip-quota administration (ISSUE 15, docs/SCHEDULING.md)."""

    def list(self) -> list[dict]:
        """Every quota row, each with live ``in_use`` chips."""
        return self._json("GET", "/api/v1/quotas")

    def get(self, tenant: str) -> dict:
        return self._json("GET", f"/api/v1/quotas/{tenant}")

    def set(self, tenant: str, chips: int) -> dict:
        return self._json("PUT", f"/api/v1/quotas/{tenant}",
                          json={"chips": int(chips)})

    def delete(self, tenant: str) -> dict:
        return self._json("DELETE", f"/api/v1/quotas/{tenant}")


class ClusterClient(BaseClient):
    """Federated cluster-registry administration (ISSUE 16,
    docs/SCHEDULING.md "Placement and spillover")."""

    def list(self) -> list[dict]:
        """Every registered cluster with its live ``healthy`` flag."""
        return self._json("GET", "/api/v1/clusters")

    def get(self, name: str) -> dict:
        return self._json("GET", f"/api/v1/clusters/{name}")

    def register(self, name: str, region: Optional[str] = None,
                 chip_type: Optional[str] = None, capacity: int = 0) -> dict:
        return self._json("PUT", f"/api/v1/clusters/{name}",
                          json={"region": region, "chipType": chip_type,
                                "capacity": int(capacity)})

    def delete(self, name: str) -> dict:
        """The death certificate: survivors re-place this cluster's runs
        without waiting to prove its pods are gone. Irreversible intent —
        only for hardware that is truly not coming back."""
        return self._json("DELETE", f"/api/v1/clusters/{name}")


class AlertClient(BaseClient):
    """SLO alert + status surface (ISSUE 20, docs/OBSERVABILITY.md)."""

    def list(self, state: Optional[str] = None) -> list[dict]:
        """Alert rows, firing first; ``state`` filters to one state."""
        path = "/api/v1/alerts"
        if state:
            path += f"?state={state}"
        return self._json("GET", path).get("alerts", [])

    def slo_status(self) -> list[dict]:
        """Live burn rates for every configured SLO."""
        return self._json("GET", "/api/v1/slo/status").get("slos", [])

    def history(self, family: str, range_s: float = 3600.0,
                at: float = 0.0) -> dict:
        """Downsampled history for one metric family."""
        return self._json(
            "GET", f"/api/v1/metrics/history?family={family}"
                   f"&range={range_s}&at={at}")


class TokenClient(BaseClient):
    """Token administration (RBAC-lite): mint/list/revoke access tokens."""

    def create(self, project: Optional[str] = None,
               label: Optional[str] = None) -> dict:
        return self._json("POST", "/api/v1/tokens",
                          json={"project": project, "label": label})

    def list(self) -> list[dict]:
        return self._json("GET", "/api/v1/tokens")

    def revoke(self, token_id: int) -> dict:
        return self._json("DELETE", f"/api/v1/tokens/{token_id}")


class RunClient(BaseClient):
    """Operations on runs; binds (project, run_uuid) like upstream."""

    def __init__(
        self,
        host: str = "http://127.0.0.1:8000",
        project: str = "default",
        run_uuid: Optional[str] = None,
        timeout: float = 30.0,
        auth_token: Optional[str] = None,
        retry: Optional[RetryPolicy] = None,
    ):
        super().__init__(host, timeout, auth_token=auth_token, retry=retry)
        self.project = project
        self.run_uuid = run_uuid

    def _rpath(self, suffix: str = "", uuid: Optional[str] = None) -> str:
        uuid = uuid or self.run_uuid
        assert uuid, "run_uuid not set"
        return f"/api/v1/{self.project}/runs/{uuid}{suffix}"

    # -- create / read -----------------------------------------------------

    def create(
        self,
        operation: Optional[V1Operation] = None,
        spec: Optional[dict] = None,
        name: Optional[str] = None,
        kind: Optional[str] = None,
        inputs: Optional[dict] = None,
        meta: Optional[dict] = None,
        tags: Optional[list] = None,
        pipeline_uuid: Optional[str] = None,
    ) -> dict:
        if operation is not None:
            spec = operation.to_dict()
            name = name or operation.name
        # inputs default server-side: the store derives them from the
        # spec's bound params (Store._params_to_inputs)
        run = self._json("POST", f"/api/v1/{self.project}/runs", json={
            "spec": spec, "name": name, "kind": kind, "inputs": inputs,
            "meta": meta, "tags": tags, "pipeline_uuid": pipeline_uuid,
        })
        self.run_uuid = run["uuid"]
        return run

    def refresh(self, uuid: Optional[str] = None) -> dict:
        return self._json("GET", self._rpath(uuid=uuid))

    def list(self, status: Optional[str] = None, pipeline_uuid: Optional[str] = None,
             created_by: Optional[str] = None,
             limit: int = 100, offset: int = 0) -> list[dict]:
        params = {"limit": limit, "offset": offset}
        if status:
            params["status"] = status
        if pipeline_uuid:
            params["pipeline_uuid"] = pipeline_uuid
        if created_by:
            params["created_by"] = created_by
        return self._json("GET", f"/api/v1/{self.project}/runs", params=params)

    def list_page(self, status: Optional[str] = None,
                  pipeline_uuid: Optional[str] = None,
                  created_by: Optional[str] = None,
                  limit: int = 100, cursor: Optional[str] = None) -> dict:
        """Cursor-paginated listing: {results, count, next_cursor,
        server_time}. Pass ``next_cursor`` back to walk deep listings in
        O(page) server work per call (OFFSET re-scans every skipped row)."""
        params: dict = {"limit": limit, "paged": 1}
        if status:
            params["status"] = status
        if pipeline_uuid:
            params["pipeline_uuid"] = pipeline_uuid
        if created_by:
            params["created_by"] = created_by
        if cursor:
            params["cursor"] = cursor
        return self._json("GET", f"/api/v1/{self.project}/runs", params=params)

    def list_since(self, since: str, status: Optional[str] = None,
                   limit: int = 500) -> dict:
        """Incremental fetch: runs changed after the opaque ``since`` token
        (a commit-ordered change sequence). Bootstrap from the FIRST
        (cursor-less) ``list_page`` response's ``server_time`` — snapshot
        it, walk the pages, then poll; continuation pages carry no token
        because a run created mid-walk never appears on later DESC pages.
        Feed each returned ``server_time`` back as the next ``since`` — a
        steady-state poller transfers O(changed rows), not the whole runs
        table, and a truncated page resumes mid-delta on the next call
        instead of losing rows. Deletions are NOT in the feed (no
        tombstones): a mirror that must drop deleted runs needs a
        periodic full re-list as its resync layer."""
        params: dict = {"limit": limit, "since": since}
        if status:
            params["status"] = status
        return self._json("GET", f"/api/v1/{self.project}/runs", params=params)

    # -- tenant quotas (ISSUE 15) ------------------------------------------

    def quotas(self) -> list[dict]:
        """Tenant quota rows, each with live ``in_use`` chips — the
        tenancy pane `polyaxon quota ls` and the dashboard render
        (admin-scoped server-side; docs/SCHEDULING.md)."""
        return self._json("GET", "/api/v1/quotas")

    def set_quota(self, tenant: str, chips: int) -> dict:
        return self._json("PUT", f"/api/v1/quotas/{tenant}",
                          json={"chips": int(chips)})

    def get_quota(self, tenant: str) -> dict:
        return self._json("GET", f"/api/v1/quotas/{tenant}")

    def delete(self, uuid: Optional[str] = None) -> dict:
        return self._json("DELETE", self._rpath(uuid=uuid))

    # -- live change feed (ISSUE 14) ---------------------------------------

    def watch_events(self, since: Optional[str] = None, *,
                     project: bool = True, stop=None,
                     connect_backoff_s: float = 0.5,
                     read_timeout_s: float = 60.0):
        """Generator over the live SSE change feed
        (``GET /api/v1/streams/runs``): yields ``{"type", "id", "data"}``
        dicts for every server event (``hello``/``run``/``delete``/
        ``heartbeat``/``resync``/``evicted``).

        Reconnect discipline (the ServeFront doctrine): **sticky** to the
        working endpoint; **rotate only on connect failures and 503s**
        (host-level verdicts — a dead host or a shedding/standby one);
        **410 = resync**: the resume token predates a store failover or
        was compacted away, so a ``{"type": "resync"}`` marker is yielded
        (re-list your state!) and the stream re-subscribes WITHOUT a
        token; **409 raises** — it is a verdict about the caller,
        identical on every replica, never retried. A mid-stream drop or
        an ``evicted`` close reconnects with ``Last-Event-ID`` — the hub
        replays the missed window, loss-free and duplicate-free.

        ``stop`` (a threading.Event) ends the generator at the next
        event/keepalive boundary."""
        import requests as _requests

        token = since
        attempt = 0
        while stop is None or not stop.is_set():
            headers = {"Accept": "text/event-stream"}
            if token:
                headers["Last-Event-ID"] = str(token)
            params = {"project": self.project} if project else {}
            url = f"{self.host}/api/v1/streams/runs"
            try:
                resp = self._session.get(
                    url, headers=headers, params=params, stream=True,
                    timeout=(self.timeout, read_timeout_s))
            except (_requests.ConnectionError,
                    _requests.Timeout):
                # host-level: rotate (sticky thereafter), bounded backoff
                self._host_idx = (self._host_idx + 1) % len(self.hosts)
                attempt += 1
                time.sleep(min(connect_backoff_s * (2 ** min(attempt, 4)),
                               5.0))
                continue
            if resp.status_code == 503:
                from ..resilience.retry import parse_retry_after

                ra = parse_retry_after(resp.headers)
                resp.close()
                self._host_idx = (self._host_idx + 1) % len(self.hosts)
                attempt += 1
                time.sleep(min(ra if ra is not None else connect_backoff_s,
                               5.0))
                continue
            if resp.status_code == 410:
                # pre-failover / compacted token: full resync — the
                # consumer must re-list, deltas resume from a fresh
                # subscription (never silently skip the gap)
                resp.close()
                token = None
                yield {"type": "resync", "id": None,
                       "data": {"reason": "410"}}
                continue
            if resp.status_code >= 400:
                body = resp.text[:500]
                resp.close()
                from ..resilience.retry import parse_retry_after

                raise ApiError(resp.status_code, body,
                               retry_after=parse_retry_after(resp.headers))
            attempt = 0
            resync = False
            received = False
            try:
                for ev in _iter_sse(resp, stop=stop):
                    received = True
                    if ev.get("id"):
                        token = ev["id"]
                    if ev["type"] == "resync":
                        resync = True
                        yield ev
                        break
                    if ev["type"] == "evicted":
                        # reconnect with Last-Event-ID: the hub replays
                        # what the bounded buffer dropped
                        yield ev
                        break
                    yield ev
                else:
                    # server closed cleanly (shutdown): reconnect with
                    # the token — nothing was lost
                    pass
            except (_requests.ConnectionError, _requests.Timeout,
                    _requests.exceptions.ChunkedEncodingError):
                pass  # mid-stream drop: reconnect with Last-Event-ID
            finally:
                resp.close()
            if resync:
                token = None
            if not received:
                # a 200 that closed before a single event (a non-streaming
                # intermediary, a server mid-drain): back off — an instant
                # re-GET would hammer the endpoint and burn an admission
                # slot per attempt (browsers honor `retry: 3000` here)
                attempt += 1
                time.sleep(min(connect_backoff_s * (2 ** min(attempt, 4)),
                               5.0))

    def watch(self, since: Optional[str] = None, *, stop=None,
              heartbeats: bool = False):
        """High-level live watch: yields ``{"type": "run", "run": {...}}``
        for every committed run delta (plus ``delete``/``resync`` — and
        ``heartbeat`` when asked). On ``resync`` the consumer must
        re-list (``list_page``) before trusting further deltas."""
        for ev in self.watch_events(since=since, stop=stop):
            if ev["type"] == "run":
                yield {"type": "run", "id": ev.get("id"), "run": ev["data"]}
            elif ev["type"] == "delete":
                yield {"type": "delete", "id": ev.get("id"),
                       "uuid": ev["data"].get("uuid")}
            elif ev["type"] == "resync":
                yield {"type": "resync"}
            elif ev["type"] == "heartbeat" and heartbeats:
                yield {"type": "heartbeat", "id": ev.get("id"),
                       "data": ev["data"]}

    # -- lifecycle ---------------------------------------------------------

    def log_status(self, status: str, reason: Optional[str] = None,
                   message: Optional[str] = None, force: bool = False) -> dict:
        return self._json("POST", self._rpath("/statuses"), json={
            "status": status, "reason": reason, "message": message, "force": force,
        })

    def get_statuses(self, uuid: Optional[str] = None) -> dict:
        return self._json("GET", self._rpath("/statuses", uuid=uuid))

    def heartbeat(self, uuid: Optional[str] = None,
                  step: Optional[int] = None,
                  anomalies: Optional[dict] = None,
                  rollbacks: Optional[int] = None,
                  incarnation: Optional[str] = None,
                  serve: Optional[dict] = None,
                  metrics: Optional[dict] = None) -> dict:
        """Renew the run's liveness lease (see docs/RESILIENCE.md): an
        executor that stops heartbeating gets zombie-reaped by the agent.
        ``step`` reports training progress (ISSUE 8) — an executor whose
        beats stay fresh while ``step`` freezes gets stall-reaped.
        ``metrics`` (ISSUE 20) is a drained ``SeriesBuffer`` payload the
        server merges into its fleet-wide metrics history."""
        body: dict = {}
        if step is not None:
            body["step"] = int(step)
        if anomalies:
            body["anomalies"] = anomalies
        if rollbacks:
            body["rollbacks"] = int(rollbacks)
        if incarnation:
            body["incarnation"] = str(incarnation)
        if serve is not None:
            body["serve"] = serve
        if metrics is not None:
            body["metrics"] = metrics
        return self._json("POST", self._rpath("/heartbeat", uuid=uuid),
                          json=body or None)

    def stop(self, uuid: Optional[str] = None) -> dict:
        return self._json("POST", self._rpath("/stop", uuid=uuid))

    def restart(self, uuid: Optional[str] = None, spec: Optional[dict] = None) -> dict:
        return self._json("POST", self._rpath("/restart", uuid=uuid),
                          json={"spec": spec} if spec else {})

    def wait(self, uuid: Optional[str] = None, timeout: float = 300.0,
             poll: float = 0.25) -> dict:
        """Block until the run reaches a terminal status."""
        deadline = time.monotonic() + timeout
        while True:
            run = self.refresh(uuid)
            if is_done(run["status"]):
                return run
            if time.monotonic() > deadline:
                raise TimeoutError(f"run {run['uuid']} still {run['status']}")
            time.sleep(poll)

    # -- data --------------------------------------------------------------

    def log_outputs(self, uuid: Optional[str] = None, **outputs: Any) -> dict:
        return self._json("POST", self._rpath("/outputs", uuid=uuid), json=outputs)

    def get_metrics(self, names: Optional[list[str]] = None,
                    uuid: Optional[str] = None) -> dict:
        params = {"names": ",".join(names)} if names else {}
        return self._json("GET", self._rpath("/metrics", uuid=uuid), params=params)

    def get_events(self, kind: str, names: Optional[list[str]] = None,
                   uuid: Optional[str] = None) -> dict:
        """Events of any V1Event kind (histogram/image/text/span/...) per
        name — the same endpoint the dashboard charts read."""
        params = {"names": ",".join(names)} if names else {}
        return self._json("GET", self._rpath(f"/events/{kind}", uuid=uuid),
                          params=params)

    def timeline(self, uuid: Optional[str] = None) -> dict:
        """The run's merged trace {run_uuid, trace_id, status, processes,
        spans: [{name, process, start, end, duration_s, meta}]} — control-
        plane lifecycle phases + pod-side training spans on one clock
        (the dashboard Timeline tab and `polyaxon timeline` render it)."""
        return self._json("GET", self._rpath("/timeline", uuid=uuid))

    def get_logs(self, offset: int = 0, uuid: Optional[str] = None) -> tuple[str, int]:
        resp = self._req("GET", self._rpath("/logs", uuid=uuid), params={"offset": offset})
        return resp.text, int(resp.headers.get("X-Log-Offset", 0))

    def artifacts_tree(self, path: str = "", uuid: Optional[str] = None) -> dict:
        return self._json("GET", self._rpath("/artifacts/tree", uuid=uuid),
                          params={"path": path})

    def download_artifact(self, path: str, dest: str, uuid: Optional[str] = None) -> str:
        resp = self._req("GET", self._rpath("/artifacts/file", uuid=uuid),
                         params={"path": path})
        with open(dest, "wb") as f:
            f.write(resp.content)
        return dest

    # -- serving (ISSUE 12) ------------------------------------------------

    def serve_endpoints(self, uuid: Optional[str] = None) -> list[str]:
        """Live replica endpoints of a `kind: service` run: the
        ``serve-endpoint-<replica>.json`` files replicas publish into the
        run's artifacts (replica 0 owns the declared port; the rest land
        on ephemeral ones), against the agent-stamped service host.
        Falls back to the stamped meta.service port when no endpoint
        file exists yet."""
        import json as _json

        run = self.refresh(uuid)
        svc = ((run.get("meta") or {}).get("service") or {})
        host = svc.get("host", "127.0.0.1")
        eps: list[tuple[int, str]] = []
        try:
            tree = self._json("GET", self._rpath("/artifacts/tree",
                                                 uuid=uuid))
            names = [f["name"] for f in tree.get("files", [])
                     if f["name"].startswith("serve-endpoint-")]
        except ApiError:
            names = []
        for name in names:
            try:
                resp = self._req("GET",
                                 self._rpath("/artifacts/file", uuid=uuid),
                                 params={"path": name})
                d = _json.loads(resp.content)
                eps.append((int(d["replica"]),
                            f"http://{host}:{int(d['port'])}"))
            except (ApiError, ValueError, KeyError, TypeError):
                continue
        if not eps and svc.get("port"):
            eps.append((0, f"http://{host}:{int(svc['port'])}"))
        return [url for _, url in sorted(eps)]

    def serve_front(self, uuid: Optional[str] = None, **kwargs: Any):
        """A request-path failover :class:`~polyaxon_tpu.client.serve.
        ServeFront` over this service run's replicas — endpoints
        re-discovered per attempt, so replica churn (kills, restarts,
        autoscale) is survived mid-conversation."""
        from .serve import ServeFront

        uuid = uuid or self.run_uuid
        return ServeFront(
            endpoints_fn=lambda: self.serve_endpoints(uuid), **kwargs)

    def log_artifact_lineage(self, artifact: Any, uuid: Optional[str] = None) -> dict:
        body = artifact.to_dict() if hasattr(artifact, "to_dict") else dict(artifact)
        return self._json("POST", self._rpath("/lineage", uuid=uuid), json=body)

    def get_lineage(self, uuid: Optional[str] = None) -> list[dict]:
        return self._json("GET", self._rpath("/lineage", uuid=uuid))
