"""API clients (upstream RunClient/ProjectClient equivalents), plus the
serve front — the request-path failover client for `kind: service`
replica fleets (ISSUE 12)."""

from .client import (
    AgentClient, AlertClient, ApiError, BaseClient, ClusterClient,
    ProjectClient, QuotaClient, RunClient, TokenClient,
)
from .serve import (  # noqa: F401
    ServeFront, ServeUnavailableError, federated_endpoints,
)
