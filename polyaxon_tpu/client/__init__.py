"""API clients (upstream RunClient/ProjectClient equivalents)."""

from .client import (
    AgentClient, ApiError, BaseClient, ProjectClient, RunClient, TokenClient,
)
