"""API clients (upstream RunClient/ProjectClient equivalents)."""

from .client import ApiError, BaseClient, ProjectClient, RunClient, TokenClient
