"""Buffered async event writers (upstream ``EventFileWriter``: user code
must never block on IO — SURVEY.md §3(d) call stack).

Layout under a run's artifacts dir (the contract the sidecar + streams
service read):

    events/metric/<name>.jsonl      one V1Event per line
    events/<kind>/<name>.jsonl      other kinds
    logs/<name>.plx.log             timestamped log lines
    outputs/...                     user artifacts
"""

from __future__ import annotations

import datetime
import os
import queue
import threading
from typing import Optional

from .events import V1Event

_SENTINEL = object()


class EventFileWriter:
    """Append V1Events to per-(kind, name) jsonl files from a writer thread."""

    def __init__(self, run_dir: str, flush_secs: float = 2.0):
        self.events_dir = os.path.join(run_dir, "events")
        os.makedirs(self.events_dir, exist_ok=True)
        self._q: "queue.Queue" = queue.Queue()
        self._files: dict[tuple[str, str], object] = {}
        self._flush_secs = flush_secs
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def add(self, kind: str, name: str, event: V1Event) -> None:
        if self._closed:
            raise RuntimeError("writer closed")
        self._q.put((kind, name, event))

    def _path(self, kind: str, name: str) -> str:
        d = os.path.join(self.events_dir, kind)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{name}.jsonl")

    def _loop(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=self._flush_secs)
            except queue.Empty:
                self._flush()
                continue
            if item is _SENTINEL:
                break
            kind, name, event = item
            f = self._files.get((kind, name))
            if f is None:
                f = open(self._path(kind, name), "a", encoding="utf-8")
                self._files[(kind, name)] = f
            f.write(event.to_jsonl() + "\n")
        self._flush()

    def _flush(self) -> None:
        # snapshot: flush() runs on the CALLER's thread while _loop may be
        # opening a first-event file — iterating the live dict races
        # ("dictionary changed size during iteration", seen in the profile
        # e2e under load)
        for f in list(self._files.values()):
            f.flush()

    def flush(self, timeout: float = 10.0) -> None:
        """Block until queued events are on disk."""
        deadline = datetime.datetime.now().timestamp() + timeout
        while not self._q.empty():
            if datetime.datetime.now().timestamp() > deadline:
                break
            threading.Event().wait(0.01)
        self._flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(_SENTINEL)
        self._thread.join(timeout=10)
        for f in list(self._files.values()):
            f.close()
        self._files.clear()


class LogWriter:
    """Timestamped line-oriented log capture to ``logs/``."""

    def __init__(self, run_dir: str, name: str = "run"):
        d = os.path.join(run_dir, "logs")
        os.makedirs(d, exist_ok=True)
        self._f = open(os.path.join(d, f"{name}.plx.log"), "a", encoding="utf-8")
        self._lock = threading.Lock()

    def write(self, line: str) -> None:
        ts = datetime.datetime.now(datetime.timezone.utc).isoformat()
        with self._lock:
            self._f.write(f"{ts} {line.rstrip()}\n")
            self._f.flush()

    def close(self) -> None:
        self._f.close()


def read_events(run_dir: str, kind: str, name: str) -> list[V1Event]:
    path = os.path.join(run_dir, "events", kind, f"{name}.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(V1Event.from_jsonl(line))
    return out


def list_event_names(run_dir: str, kind: str) -> list[str]:
    d = os.path.join(run_dir, "events", kind)
    if not os.path.isdir(d):
        return []
    return sorted(os.path.splitext(f)[0] for f in os.listdir(d) if f.endswith(".jsonl"))
