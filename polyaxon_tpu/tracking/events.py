"""Event schema — the traceml ``V1Event`` equivalent (SURVEY.md §2
"Traceml" row, §5 "Metrics/logging": jsonl per metric name, one event per
line, so dashboards/CLIs can tail incrementally)."""

from __future__ import annotations

import datetime
import json
from typing import Any, Optional, Union

from pydantic import Field

from ..schemas.base import BaseSchema


class V1EventKind:
    METRIC = "metric"
    IMAGE = "image"
    HISTOGRAM = "histogram"
    AUDIO = "audio"
    VIDEO = "video"
    TEXT = "text"
    HTML = "html"
    CHART = "chart"
    CURVE = "curve"
    CONFUSION = "confusion"
    ARTIFACT = "artifact"
    MODEL = "model"
    DATAFRAME = "dataframe"
    SPAN = "span"

    ALL = {METRIC, IMAGE, HISTOGRAM, AUDIO, VIDEO, TEXT, HTML, CHART, CURVE,
           CONFUSION, ARTIFACT, MODEL, DATAFRAME, SPAN}


class V1EventImage(BaseSchema):
    path: Optional[str] = None
    width: Optional[int] = None
    height: Optional[int] = None


class V1EventHistogram(BaseSchema):
    values: list[float] = Field(default_factory=list)
    counts: list[float] = Field(default_factory=list)


class V1EventArtifact(BaseSchema):
    kind: Optional[str] = None
    path: Optional[str] = None


class V1EventCurve(BaseSchema):
    """An x/y curve sampled at one step (upstream ``V1EventCurve``:
    roc / pr / calibration curves — VERDICT weak #7)."""

    x: list[float] = Field(default_factory=list)
    y: list[float] = Field(default_factory=list)
    annotation: Optional[str] = None  # e.g. "auc=0.93"


class V1EventConfusion(BaseSchema):
    """A confusion matrix at one step (upstream
    ``V1EventConfusionMatrix``): ``x``/``y`` are the predicted/actual
    label axes, ``z`` the row-major counts."""

    x: list[Any] = Field(default_factory=list)
    y: list[Any] = Field(default_factory=list)
    z: list[list[float]] = Field(default_factory=list)


class V1EventSpan(BaseSchema):
    """Tracing span (SURVEY.md §5 tracing: jax.profiler sections logged as
    spans so the UI can render a timeline)."""

    name: Optional[str] = None
    start: Optional[float] = None
    end: Optional[float] = None
    meta: Optional[dict[str, Any]] = None


class V1Event(BaseSchema):
    timestamp: Optional[str] = None
    step: Optional[int] = None
    metric: Optional[float] = None
    image: Optional[V1EventImage] = None
    histogram: Optional[V1EventHistogram] = None
    text: Optional[str] = None
    html: Optional[str] = None
    artifact: Optional[V1EventArtifact] = None
    span: Optional[V1EventSpan] = None
    curve: Optional[V1EventCurve] = None
    confusion: Optional[V1EventConfusion] = None

    @classmethod
    def make(cls, step: Optional[int] = None, **kwargs: Any) -> "V1Event":
        return cls(
            timestamp=datetime.datetime.now(datetime.timezone.utc).isoformat(),
            step=step,
            **kwargs,
        )

    @property
    def kind(self) -> str:
        for k in ("metric", "image", "histogram", "text", "html", "artifact",
                  "span", "curve", "confusion"):
            if getattr(self, k) is not None:
                return k
        return V1EventKind.METRIC

    def to_jsonl(self) -> str:
        return json.dumps(self.to_dict(), separators=(",", ":"))

    @classmethod
    def from_jsonl(cls, line: str) -> "V1Event":
        return cls.from_dict(json.loads(line))


class V1ArtifactKind:
    """Lineage artifact kinds (upstream ``V1ArtifactKind``)."""

    MODEL = "model"
    AUDIO = "audio"
    VIDEO = "video"
    DATASET = "dataset"
    DATAFRAME = "dataframe"
    IMAGE = "image"
    TENSORBOARD = "tensorboard"
    CODEREF = "coderef"
    FILE = "file"
    DIR = "dir"
    DOCKERFILE = "dockerfile"
    METRIC = "metric"
    ENV = "env"
    CHECKPOINT = "checkpoint"
    PROFILE = "profile"  # jax.profiler trace dirs

    ALL = {MODEL, AUDIO, VIDEO, DATASET, DATAFRAME, IMAGE, TENSORBOARD,
           CODEREF, FILE, DIR, DOCKERFILE, METRIC, ENV, CHECKPOINT, PROFILE}


class V1RunArtifact(BaseSchema):
    """Lineage record linking a run to an artifact."""

    name: Optional[str] = None
    kind: Optional[str] = None
    path: Optional[str] = None
    state: Optional[str] = None
    summary: Optional[dict[str, Any]] = None
    is_input: Optional[bool] = None
