"""Tracking/lineage (traceml equivalent — SURVEY.md §2 "Traceml" row)."""

from .events import (
    V1ArtifactKind,
    V1Event,
    V1EventArtifact,
    V1EventConfusion,
    V1EventCurve,
    V1EventHistogram,
    V1EventImage,
    V1EventKind,
    V1EventSpan,
    V1RunArtifact,
)
from .resources import ResourceLogger
from .run import Run, end, get_run, init, log_artifact, log_metrics, log_outputs
from .spool import EventSpool
from .writer import EventFileWriter, LogWriter, list_event_names, read_events
