"""Outage-proof pod-side API writes (ISSUE 7 tentpole (c)).

A training pod's API-bound writes — statuses, outputs, heartbeats,
lineage — must survive a control-plane outage without killing or
stalling the run. When the API is unreachable, :class:`EventSpool`
captures each write as one JSONL record (idempotency key + monotonic
spool seq) in an append-only file under the run's artifacts dir, fsynced
per record; on reconnect the records replay IN ORDER, each acked
durably only after the server accepted it, so a crash mid-replay resumes
exactly where it left off — no gaps, and no duplicates beyond the one
ambiguous record a crash-between-accept-and-ack can re-send (which the
server-side verbs absorb: transitions dedupe via the status machine,
outputs merge by key, heartbeats are idempotent by nature).

The spool is deliberately dumb storage: ordering and delivery policy
live in :meth:`replay`'s caller (``tracking.Run``), which also enforces
the queue discipline — once anything is spooled, every later write is
appended BEHIND it until a full flush succeeds, so the server always
observes the pod's writes in emission order.
"""

from __future__ import annotations

import json
import os
import threading
import uuid as uuid_mod
from datetime import datetime, timezone
from typing import Callable, Optional


class EventSpool:
    """Append-only JSONL spool with a durable ack cursor.

    Files under ``<run_dir>/.spool/``: ``<name>.jsonl`` (the records) and
    ``<name>.ack`` (how many leading records the server has accepted,
    written atomically tmp+rename). A truncated final line (crash mid-
    append) is treated as never-written: the record's caller saw the
    append fail or died with it — either way the write never happened
    from the server's point of view."""

    def __init__(self, run_dir: str, name: str = "api", metrics=None,
                 labels: Optional[dict] = None):
        self.dir = os.path.join(run_dir, ".spool")
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, f"{name}.jsonl")
        self._ack_path = os.path.join(self.dir, f"{name}.ack")
        self._lock = threading.RLock()
        self._heal_tail()
        self._acked = self._read_ack()
        self._count = len(self._read_records())
        if metrics is not None:
            metrics.gauge(
                "polyaxon_tracking_spool_depth",
                "API writes spooled locally, awaiting replay",
                labels=labels, value_fn=lambda: float(self.depth))

    def _heal_tail(self) -> None:
        """Truncate a torn final line (crash mid-append). Healing must
        happen BEFORE the first append of a restarted attempt: appending
        onto a newline-less fragment would weld the new record onto the
        torn one into a single unparseable line, making it — and every
        record behind it — permanently unreplayable."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size == 0:
            return
        with open(self.path, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) == b"\n":
                return
            f.seek(0)
            cut = f.read().rfind(b"\n") + 1
            f.truncate(cut)
            f.flush()
            os.fsync(f.fileno())

    def _read_ack(self) -> int:
        try:
            with open(self._ack_path, encoding="utf-8") as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _write_ack(self) -> None:
        tmp = self._ack_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(self._acked))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ack_path)

    def _read_records(self) -> list[dict]:
        if not os.path.exists(self.path):
            return []
        out: list[dict] = []
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    break  # torn tail: the append never completed
        return out

    @property
    def depth(self) -> int:
        """Records spooled and not yet acked."""
        with self._lock:
            return max(self._count - self._acked, 0)

    def append(self, verb: str, kwargs: dict) -> dict:
        """Durably spool one API write: ``verb`` is the client method to
        replay, ``kwargs`` its (JSON-serializable) arguments."""
        with self._lock:
            rec = {
                "key": uuid_mod.uuid4().hex,
                "seq": self._count + 1,
                "verb": verb,
                "kwargs": kwargs,
                "ts": datetime.now(timezone.utc).isoformat(),
            }
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._count += 1
            return rec

    def pending(self) -> list[dict]:
        with self._lock:
            return self._read_records()[self._acked:]

    def replay(self, send: Callable[[dict], None]) -> int:
        """Deliver pending records in order: ``send(rec)`` raising aborts
        the replay (the remainder stays spooled, order intact); each
        success acks durably before the next record goes out. When the
        spool fully drains, the files are compacted away. Returns the
        number of records delivered."""
        with self._lock:
            recs = self.pending()
            done = 0
            for rec in recs:
                send(rec)  # raises to abort — rec stays pending
                self._acked += 1
                self._write_ack()
                done += 1
            if done and self.depth == 0:
                # ack file FIRST: if only the records file were removed,
                # a restarted pod would read ack=N over 0 records and
                # silently swallow the next N spooled writes (a permanent
                # gap). Losing the ack first fails toward a duplicate
                # replay, which the idempotent server verbs absorb.
                try:
                    os.remove(self._ack_path)
                    os.remove(self.path)
                except OSError:
                    pass
                self._count = 0
                self._acked = 0
            return done


__all__ = ["EventSpool"]
