"""Host/TPU resource logger (upstream traceml ``ResourceLogger`` used
psutil/pynvml; the TPU equivalent reads jax.local_devices memory stats)."""

from __future__ import annotations

import threading
import time
from typing import Optional

from .run import Run


def sample_host() -> dict:
    import psutil

    vm = psutil.virtual_memory()
    return {
        "host_cpu_percent": psutil.cpu_percent(interval=None),
        "host_mem_percent": vm.percent,
        "host_mem_used_gib": vm.used / 2**30,
    }


def sample_tpu() -> dict:
    """Per-device HBM stats via jax memory_stats (no-op off-accelerator)."""
    out: dict = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            if "bytes_in_use" in stats:
                out[f"tpu{d.id}_hbm_gib"] = stats["bytes_in_use"] / 2**30
            if "peak_bytes_in_use" in stats:
                out[f"tpu{d.id}_hbm_peak_gib"] = stats["peak_bytes_in_use"] / 2**30
    except Exception:
        pass
    return out


class ResourceLogger:
    """Background thread logging host + TPU resource metrics every
    ``interval`` seconds to the run's event files."""

    def __init__(self, run: Run, interval: float = 10.0, tpu: bool = True):
        self.run = run
        self.interval = interval
        self.tpu = tpu
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ResourceLogger":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                metrics = sample_host()
                if self.tpu:
                    metrics.update(sample_tpu())
                self.run.log_metrics(**metrics)
            except Exception:  # noqa: BLE001 — telemetry must never kill a run
                # (e.g. psutil absent in a user image): stop sampling, the
                # training loop is the product
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
