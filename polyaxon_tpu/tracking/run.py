"""User-facing tracking API (upstream ``from polyaxon import tracking``):

    from polyaxon_tpu import tracking
    tracking.init()                       # attaches via PLX_* env in-cluster
    tracking.log_metrics(step=i, loss=0.3, mfu=0.46)
    tracking.log_artifact("model", path, kind="checkpoint")

Events land in the run's artifacts dir (writer.py layout); when an API host
is configured, statuses/outputs also post there. Works fully offline — the
same code runs on a laptop or a TPU host pod (SURVEY.md §3(d))."""

from __future__ import annotations

import os
import time
import traceback
import uuid as uuid_mod
from typing import Any, Optional

from .events import (
    V1Event,
    V1EventArtifact,
    V1EventConfusion,
    V1EventCurve,
    V1EventHistogram,
    V1EventImage,
    V1EventSpan,
    V1RunArtifact,
)
from .spool import EventSpool
from .writer import EventFileWriter, LogWriter

# Env contract injected by the compiler/operator (compiler/converter.py).
ENV_RUN_UUID = "PLX_RUN_UUID"
ENV_PROJECT = "PLX_PROJECT"
ENV_ARTIFACTS_PATH = "PLX_ARTIFACTS_PATH"
ENV_API_HOST = "PLX_API_HOST"
# trace correlation (ISSUE 5): pod-side spans join the control plane's run
# timeline through this id (defaults to the run uuid when absent)
ENV_TRACE_ID = "POLYAXON_TRACE_ID"


def _pod_retry():
    """The pod-side client's retry: SHORT. A control-plane outage routes
    writes to the local spool (ISSUE 7) — a long in-line retry would
    stall the training step loop for the whole backoff budget at every
    log call, which is exactly the 'outage stalls the run' failure the
    spool exists to prevent. One quick re-try rides out a blip; anything
    longer is the spool's job."""
    from ..resilience.retry import RetryPolicy

    return RetryPolicy(max_attempts=2, base_delay=0.1, max_delay=0.5,
                       deadline=3.0)


def _spoolable(exc: BaseException) -> bool:
    """Failures the spool absorbs: the API is unreachable or transiently
    failing (connection errors, timeouts, 5xx/429 after the short retry).
    Terminal verdicts — fencing 409s, epoch 410s, plain 4xx — are NOT
    spooled: replaying them later would get the same answer."""
    status = getattr(exc, "status", None)
    if status is not None:
        return status in (429, 500, 502, 503, 504)
    # requests exceptions subclass OSError; TimeoutError/ConnectionError
    # cover the in-proc and socket paths
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


class Run:
    """A tracked run: event/log writers + optional API client binding."""

    def __init__(
        self,
        run_uuid: Optional[str] = None,
        project: Optional[str] = None,
        artifacts_path: Optional[str] = None,
        api_host: Optional[str] = None,
        client: Any = None,
    ):
        self.run_uuid = run_uuid or os.environ.get(ENV_RUN_UUID) or uuid_mod.uuid4().hex
        self.project = project or os.environ.get(ENV_PROJECT, "default")
        base = artifacts_path or os.environ.get(ENV_ARTIFACTS_PATH)
        if base is None:
            base = os.path.join(os.getcwd(), ".plx", "runs", self.run_uuid)
        self.run_dir = base
        self.trace_id = os.environ.get(ENV_TRACE_ID) or self.run_uuid
        # one id per tracking PROCESS: progress reports carry it so the
        # store's train-counter delta accounting can tell "restarted
        # attempt, cumulatives reset" from "stale relay of an old value"
        self.incarnation = uuid_mod.uuid4().hex[:12]
        os.makedirs(self.run_dir, exist_ok=True)
        self._writer = EventFileWriter(self.run_dir)
        self._logger = LogWriter(self.run_dir)
        self._outputs: dict[str, Any] = {}
        self._lineage: list[V1RunArtifact] = []
        api_host = api_host or os.environ.get(ENV_API_HOST)
        if client is None and api_host:
            from ..client import RunClient

            # api_host may be an ordered, comma-separated endpoint list
            # (primary + standbys): the client rotates through it (ISSUE 7)
            client = RunClient(host=api_host, project=self.project,
                               run_uuid=self.run_uuid, retry=_pod_retry())
        self.client = client
        # outage-proof API writes (ISSUE 7): when the control plane is
        # unreachable, statuses/outputs/heartbeats/lineage spool to an
        # append-only local file and replay in order on reconnect. Only
        # API-bound runs carry a spool — a client-less (offline) run has
        # nothing to spool and must not litter its artifacts dir. A
        # leftover spool from a previous incarnation of this run (pod
        # crashed mid-outage) is picked up and drained here.
        self._spool = (EventSpool(self.run_dir)
                       if self.client is not None else None)
        self.spool_retry_interval = 5.0
        self._spool_probe_at = 0.0
        if self._spool is not None and self._spool.depth:
            try:
                self.flush_spool()
            except Exception:
                pass

    # -- API writes through the outage spool (ISSUE 7) ---------------------

    @property
    def spool_depth(self) -> int:
        """API writes waiting locally for the control plane to come back."""
        return self._spool.depth if self._spool is not None else 0

    def _api(self, verb: str, /, **kwargs: Any) -> Any:
        """One API-bound write. While the spool is non-empty every write
        is APPENDED behind it (emission order is part of the no-gaps
        contract), with a rate-limited reconnect probe; a fresh failure
        spools the write instead of raising into the training loop.
        ``verb`` is positional-only so a user OUTPUT named "verb"
        (``log_outputs(verb=...)``) cannot collide with it."""
        if self.client is None:
            return None
        if self._spool.depth:
            if time.monotonic() >= self._spool_probe_at:
                try:
                    self.flush_spool()
                except Exception:
                    pass
            if self._spool.depth:
                self._spool.append(verb, kwargs)
                return None
        try:
            return getattr(self.client, verb)(**kwargs)
        except Exception as e:
            if not _spoolable(e):
                raise
            self._spool.append(verb, kwargs)
            self._spool_probe_at = (time.monotonic()
                                    + self.spool_retry_interval)
            return None

    def flush_spool(self) -> int:
        """Replay spooled writes in order. Unreachable-API failures abort
        the replay (everything undelivered stays spooled, order intact)
        and re-arm the probe timer; terminal rejections (a late status on
        a stopped run, a 4xx) are logged and DROPPED — holding the queue
        hostage to one unreplayable record would gap everything behind
        it. Returns records delivered (dropped ones count: they are
        resolved)."""
        if self.client is None or self._spool is None:
            return 0

        def _send(rec: dict) -> None:
            try:
                getattr(self.client, rec["verb"])(**rec["kwargs"])
            except Exception as e:
                if _spoolable(e):
                    self._spool_probe_at = (time.monotonic()
                                            + self.spool_retry_interval)
                    raise
                traceback.print_exc()  # terminal: drop, keep draining

        return self._spool.replay(_send)

    def heartbeat(self, step: Optional[int] = None,
                  anomalies: Optional[dict] = None,
                  rollbacks: Optional[int] = None,
                  serve: Optional[dict] = None,
                  metrics: Optional[dict] = None) -> None:
        """Renew this run's liveness lease (spooled through an outage so
        the post-failover reaper sees the replayed beats, not a corpse).

        ``step`` (ISSUE 8) is the training-progress field the stall-aware
        reaper watches: a pod whose heartbeats stay fresh while ``step``
        freezes is wedged, not healthy. ``anomalies``/``rollbacks`` are
        the pod's CUMULATIVE divergence-guard counters — the store turns
        them into the ``polyaxon_train_*`` metric families by delta.

        ``metrics`` (ISSUE 20) is a drained
        :class:`~polyaxon_tpu.obs.history.SeriesBuffer` payload: the
        pod's local history points, merged into the server recorder's
        fleet rollup. Points carry AGES, so spool replay after an outage
        lands them in the past where they belong (at drain-time
        accuracy), never stacked on \"now\"."""
        kw: dict[str, Any] = {}
        if step is not None:
            kw["step"] = int(step)
        if anomalies:
            kw["anomalies"] = {k: int(v) for k, v in anomalies.items()}
        if rollbacks:
            kw["rollbacks"] = int(rollbacks)
        if serve is not None:
            # serve traffic snapshot (ISSUE 9): cumulative counters +
            # instantaneous gauges + drained TTFT/inter-token samples; the
            # store deltas/aggregates per reporter incarnation
            kw["serve"] = dict(serve)
        if metrics is not None:
            kw["metrics"] = dict(metrics)
        if anomalies or rollbacks or serve is not None or metrics is not None:
            kw["incarnation"] = self.incarnation
        self._api("heartbeat", **kw)

    #: run-dir file the agent-side sidecar reads to bridge pod progress
    #: into store heartbeats for runs with no API client (offline pods)
    PROGRESS_FILE = "progress.json"

    def report_progress(self, step: int, anomalies: Optional[dict] = None,
                        rollbacks: Optional[int] = None) -> None:
        """Publish training progress: atomically write ``progress.json``
        into the run dir (tmp + rename — the sidecar never reads a torn
        file) AND renew the API heartbeat with the ``step`` field. The
        builtin runtime calls this rate-limited from the training loop."""
        import json

        payload: dict[str, Any] = {"step": int(step), "at": time.time(),
                                   "incarnation": self.incarnation}
        if anomalies:
            payload["anomalies"] = {k: int(v) for k, v in anomalies.items()}
        if rollbacks:
            payload["rollbacks"] = int(rollbacks)
        tmp = os.path.join(self.run_dir, "." + self.PROGRESS_FILE + ".tmp")
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, os.path.join(self.run_dir, self.PROGRESS_FILE))
        except OSError:
            pass  # progress publishing must never fail the training loop
        self.heartbeat(step=step, anomalies=payload.get("anomalies"),
                       rollbacks=payload.get("rollbacks"))

    def flush(self) -> None:
        """Flush buffered events/logs to disk NOW — the watchdog calls
        this right before a hard exit so the training_stalled span and
        the stack dump survive the process."""
        self._writer.flush()

    # -- logging -----------------------------------------------------------

    def log_metrics(self, step: Optional[int] = None, **metrics: float) -> None:
        for name, value in metrics.items():
            self._writer.add("metric", name, V1Event.make(step=step, metric=float(value)))

    def log_metric(self, name: str, value: float, step: Optional[int] = None) -> None:
        self.log_metrics(step=step, **{name: value})

    def log_text(self, name: str, text: str, step: Optional[int] = None) -> None:
        self._writer.add("text", name, V1Event.make(step=step, text=text))

    def log_histogram(
        self, name: str, values: list[float], counts: list[float], step: Optional[int] = None
    ) -> None:
        self._writer.add(
            "histogram", name,
            V1Event.make(step=step, histogram=V1EventHistogram(values=values, counts=counts)),
        )

    def log_image(self, name: str, image: Any, step: Optional[int] = None) -> None:
        """Log an image event (upstream traceml `log_image`). ``image`` is a
        path to an existing image file (copied into the run's assets) or an
        HxW / HxWx3 array (f32 in [0,1] or uint8; saved as PNG). The event
        references the run-relative path — the streams API serves it and
        the dashboard renders the latest image per name."""
        import shutil

        # TensorBoard-style names ("val/sample") become subdirectories;
        # ".."/absolute components are rejected — an event name must never
        # write outside the run's assets dir
        parts = [p for p in str(name).replace("\\", "/").split("/") if p]
        if not parts or any(p == ".." for p in parts):
            raise ValueError(f"bad image name {name!r}")
        assets_rel = os.path.join("assets", "images", *parts[:-1])
        leaf = parts[-1]
        os.makedirs(os.path.join(self.run_dir, assets_rel), exist_ok=True)
        suffix = f"_{step}" if step is not None else ""
        width = height = None
        if isinstance(image, (str, os.PathLike)):
            src = str(image)
            ext = os.path.splitext(src)[1] or ".png"
            rel = os.path.join(assets_rel, f"{leaf}{suffix}{ext}")
            shutil.copyfile(src, os.path.join(self.run_dir, rel))
        else:
            import numpy as np

            arr = np.asarray(image)
            if arr.dtype != np.uint8:
                arr = (np.clip(np.asarray(arr, dtype=np.float64), 0.0, 1.0)
                       * 255).astype(np.uint8)
            from PIL import Image as _Image

            rel = os.path.join(assets_rel, f"{leaf}{suffix}.png")
            _Image.fromarray(arr).save(os.path.join(self.run_dir, rel))
            height, width = int(arr.shape[0]), int(arr.shape[1])
        self._writer.add(
            "image", name,
            V1Event.make(step=step, image=V1EventImage(
                path=rel, width=width, height=height)),
        )

    def log_span(self, name: str, start: float, end: float, **meta: Any) -> None:
        # every span carries the trace id so the timeline assembler can
        # join pod-side spans to the control-plane lifecycle (obs/trace.py)
        meta.setdefault("trace_id", self.trace_id)
        self._writer.add(
            "span", name,
            V1Event.make(span=V1EventSpan(name=name, start=start, end=end, meta=meta or None)),
        )

    def log_curve(self, name: str, x: list, y: list,
                  annotation: Optional[str] = None,
                  step: Optional[int] = None) -> None:
        """Log an x/y curve event (roc / pr / calibration — VERDICT weak
        #7). The Metrics tab charts the latest curve per name."""
        self._writer.add(
            "curve", name,
            V1Event.make(step=step, curve=V1EventCurve(
                x=[float(v) for v in x], y=[float(v) for v in y],
                annotation=annotation)),
        )

    def log_confusion(self, name: str, x: list, y: list,
                      z: list, step: Optional[int] = None) -> None:
        """Log a confusion-matrix event: ``x``/``y`` label axes and
        row-major counts ``z``. Rendered as a heat-shaded matrix."""
        self._writer.add(
            "confusion", name,
            V1Event.make(step=step, confusion=V1EventConfusion(
                x=list(x), y=list(y),
                z=[[float(v) for v in row] for row in z])),
        )

    def log_line(self, line: str) -> None:
        self._logger.write(line)

    # -- outputs / lineage -------------------------------------------------

    def log_outputs(self, **outputs: Any) -> None:
        self._outputs.update(outputs)
        self._api("log_outputs", **outputs)

    def log_artifact(
        self, name: str, path: str, kind: str = "file", is_input: bool = False,
        summary: Optional[dict] = None,
    ) -> None:
        art = V1RunArtifact(name=name, kind=kind, path=path, is_input=is_input, summary=summary)
        self._lineage.append(art)
        self._writer.add(
            "artifact", name,
            V1Event.make(artifact=V1EventArtifact(kind=kind, path=path)),
        )
        # spooled as the dict form (JSON round-trippable); the client
        # accepts both shapes
        self._api("log_artifact_lineage", artifact=art.to_dict())

    @property
    def outputs_dir(self) -> str:
        d = os.path.join(self.run_dir, "outputs")
        os.makedirs(d, exist_ok=True)
        return d

    # -- lifecycle ---------------------------------------------------------

    def log_status(self, status: str, reason: Optional[str] = None, message: Optional[str] = None) -> None:
        self._api("log_status", status=status, reason=reason, message=message)

    def end(self, status: Optional[str] = None) -> None:
        self._writer.flush()
        if self._outputs:
            # durable copy for the offline path: the agent merges this into
            # the store when the run finishes (scheduler/agent.py)
            import json

            with open(os.path.join(self.run_dir, "outputs.json"), "w", encoding="utf-8") as f:
                json.dump(self._outputs, f)
            self._api("log_outputs", **self._outputs)
        if status:
            self.log_status(status)
        if self._spool is not None and self._spool.depth:
            # last chance to drain before the process exits; whatever
            # stays is durable on disk — a restarted attempt (same run
            # dir) picks it up, and the agent's terminal outputs.json
            # merge covers the outputs either way
            try:
                self.flush_spool()
            except Exception:
                pass
        self._writer.close()
        self._logger.close()
        global _active
        if _active is self:
            # a later get_run() must mint a fresh Run, not hand back this
            # one with closed writers (matters for in-proc sequential runs)
            _active = None


# -- module-level convenience (upstream `tracking.init()` pattern) ----------

_active: Optional[Run] = None


def init(**kwargs: Any) -> Run:
    global _active
    _active = Run(**kwargs)
    return _active


def get_run() -> Run:
    if _active is None:
        return init()
    return _active


def log_metrics(step: Optional[int] = None, **metrics: float) -> None:
    get_run().log_metrics(step=step, **metrics)


def log_outputs(**outputs: Any) -> None:
    get_run().log_outputs(**outputs)


def log_artifact(name: str, path: str, kind: str = "file", **kw: Any) -> None:
    get_run().log_artifact(name, path, kind=kind, **kw)


def end(status: Optional[str] = None) -> None:
    global _active
    if _active is not None:
        _active.end(status)
        _active = None
