"""API server runner: standalone (``python -m polyaxon_tpu.api``) or
embedded in-process for the local runtime and tests."""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from aiohttp import web

from .app import ApiApp
from .store import Store


class ApiServer:
    """Runs the aiohttp app on a background thread with its own event loop.

    ``start()`` returns once the socket is bound; ``port=0`` picks a free
    port (tests). The in-process scheduler can share ``self.store``.
    """

    def __init__(
        self,
        db_path: str = ":memory:",
        artifacts_root: str = ".plx/artifacts",
        host: str = "127.0.0.1",
        port: int = 8000,
        auth_token: "Optional[str]" = None,
        extra_middlewares: "Optional[list]" = None,
        store: "Optional[Store]" = None,
        rate_limit: "Optional[float]" = None,
        rate_limit_burst: "Optional[float]" = None,
    ):
        self.store = store if store is not None else Store(db_path)
        self.api = ApiApp(self.store, artifacts_root, auth_token=auth_token,
                          extra_middlewares=extra_middlewares,
                          rate_limit=rate_limit,
                          rate_limit_burst=rate_limit_burst)
        self.host = host
        self.port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._runner: Optional[web.AppRunner] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=15):
            raise RuntimeError("API server failed to start")
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _start():
            self._runner = web.AppRunner(self.api.app)
            await self._runner.setup()
            site = web.TCPSite(self._runner, self.host, self.port)
            await site.start()
            # resolve the actual port when 0 was requested
            server = site._server
            if server and server.sockets:
                self.port = server.sockets[0].getsockname()[1]
            self._started.set()

        self._loop.run_until_complete(_start())
        self._loop.run_forever()

    def stop(self) -> None:
        if self._loop is None:
            return

        async def _cleanup():
            if self._runner:
                await self._runner.cleanup()

        fut = asyncio.run_coroutine_threadsafe(_cleanup(), self._loop)
        try:
            fut.result(timeout=10)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._thread:
                self._thread.join(timeout=10)


def main() -> None:
    import argparse

    p = argparse.ArgumentParser("polyaxon_tpu API server")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--db", default=".plx/db.sqlite")
    p.add_argument("--artifacts-root", default=".plx/artifacts")
    p.add_argument("--standby-of", default=None, metavar="URL",
                   help="run as a warm standby of the primary API at URL: "
                        "serve reads while tailing its changelog (writes "
                        "answer 503), bootstrap from its snapshot when the "
                        "local db is empty, and promote when the primary "
                        "goes silent (docs/RESILIENCE.md)")
    p.add_argument("--promote-after", type=float, default=10.0,
                   help="with --standby-of: seconds of primary silence "
                        "before self-promotion; <=0 keeps promotion manual")
    p.add_argument("--replication-poll", type=float, default=0.5,
                   help="with --standby-of: changelog tail interval (s)")
    p.add_argument("--rate-limit", type=float, default=0.0,
                   help="per-tenant API write rate (requests/s, token "
                        "bucket keyed on the auth token's tenant); over-"
                        "limit writes answer 429 + Retry-After. <=0 "
                        "disables (docs/SCHEDULING.md)")
    p.add_argument("--rate-limit-burst", type=float, default=0.0,
                   help="token-bucket burst size (default 2x the rate)")
    p.add_argument("--compact-every", type=float, default=900.0,
                   help="changelog compaction interval (snapshot + prune, "
                        "keeping a 10k-row tail margin); <=0 disables — "
                        "the changelog then grows one row per write")
    p.add_argument("--store-shards", type=int, default=0,
                   help="partition the run space over K independent "
                        "SQLite shards (ISSUE 18), each with its own "
                        "writer lock — --db becomes a DIRECTORY of "
                        "shard-NN.sqlite files. 0 keeps the single-file "
                        "store. The shard count is claimed first-writer-"
                        "wins in the store config; reopening with a "
                        "different K is refused")
    args = p.parse_args()
    import os as _os

    store = None
    if args.store_shards > 0:
        from .sharded_store import ShardedStore

        store = ShardedStore(args.db, shards=args.store_shards)
    server = ApiServer(
        args.db, args.artifacts_root, args.host, args.port,
        store=store,
        rate_limit=(args.rate_limit if args.rate_limit > 0 else None),
        rate_limit_burst=(args.rate_limit_burst
                          if args.rate_limit_burst > 0 else None))
    data_dir = (args.db if args.store_shards > 0
                else _os.path.dirname(args.db)) or "."
    standby = None
    if args.standby_of:
        from .replication import make_standby

        standby = make_standby(
            args.standby_of, server.store, data_dir,
            promote_after=(args.promote_after
                           if args.promote_after > 0 else None),
            poll_interval=args.replication_poll).start()
    compactor = None
    if args.compact_every > 0:
        from .replication import ChangelogCompactor

        compactor = ChangelogCompactor(
            server.store, _os.path.join(data_dir, ".snapshots"),
            interval=args.compact_every).start()
    server.start()
    role = (f"warm standby of {args.standby_of}" if standby else "primary")
    print(f"polyaxon_tpu API listening on {server.url} ({role})")

    # graceful SIGTERM (ISSUE 4 satellite): finish in-flight requests via
    # AppRunner.cleanup (aiohttp drains open handlers), then exit 0
    import signal
    import threading as _threading

    drain = _threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: drain.set())
    def _teardown():
        if compactor is not None:
            compactor.stop()
        if standby is not None:
            standby.stop()
        server.stop()

    try:
        while not drain.wait(timeout=3600):
            pass
        print("SIGTERM: draining API server")
        _teardown()
    except KeyboardInterrupt:
        _teardown()


if __name__ == "__main__":
    main()
