"""REST + streams API (aiohttp) — upstream's Django API + ASGI streams
service collapsed into one async app (SURVEY.md §2 "API service"/"Streams
service" rows; §3(e) read path).

Endpoints (all JSON unless noted):
    GET  /healthz
    GET  /metrics                                   Prometheus text exposition
    GET  /api/v1/stats                              JSON twin of /metrics + lease
    GET  /api/v1/metrics/history                    ?family=&range=&at= ring-buffer
                                                    history (fleet rollup)
    GET  /api/v1/alerts                             ?state= alert table
    GET  /api/v1/slo/status                         burn rates per SLO spec
    GET  /api/v1/{project}/runs/{uuid}/timeline     lifecycle + pod span trace
    GET|POST /api/v1/projects
    GET  /api/v1/projects/{project}
    POST /api/v1/{project}/runs                     create (operation spec body)
    GET  /api/v1/{project}/runs                     list (?status=&limit=&offset=;
                                                    ?paged/?cursor/?since -> envelope)
    GET|DELETE /api/v1/{project}/runs/{uuid}
    POST /api/v1/{project}/runs/{uuid}/statuses     {status, reason?, message?}
    GET  /api/v1/{project}/runs/{uuid}/statuses
    POST /api/v1/{project}/runs/{uuid}/outputs      merged into run.outputs
    POST /api/v1/{project}/runs/{uuid}/stop
    POST /api/v1/{project}/runs/{uuid}/restart      (cloning, SURVEY.md §5)
    GET  /api/v1/{project}/runs/{uuid}/metrics      ?names=a,b -> events
    GET  /api/v1/{project}/runs/{uuid}/events/{kind}
    GET  /api/v1/{project}/runs/{uuid}/logs         ?offset=N (tail; text/plain)
    GET  /api/v1/{project}/runs/{uuid}/artifacts/tree ?path=
    GET  /api/v1/{project}/runs/{uuid}/artifacts/file ?path= (download)
    POST|GET /api/v1/{project}/runs/{uuid}/lineage
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import time
from typing import Optional

from aiohttp import web

from ..schemas.statuses import V1Statuses
from ..tracking.writer import list_event_names, read_events
from .store import Store

_PATH_PARAM_RE = re.compile(r"\{(\w+)\}")


def run_artifacts_dir(artifacts_root: str, project: str, uuid: str) -> str:
    return os.path.join(artifacts_root, project, uuid)


def _json(data, status=200, headers=None):
    return web.json_response(data, status=status, headers=headers)


def _not_found(msg="not found"):
    return _json({"error": msg}, status=404)


class ApiApp:
    def __init__(self, store: Store, artifacts_root: str,
                 auth_token: Optional[str] = None,
                 extra_middlewares: Optional[list] = None,
                 rate_limit: Optional[float] = None,
                 rate_limit_burst: Optional[float] = None):
        """``extra_middlewares`` run BEFORE auth — the chaos harness
        injects its flaky-HTTP middleware here (resilience/chaos.py).

        ``rate_limit`` (requests/second) arms per-tenant token buckets on
        the WRITE endpoints (ISSUE 15): a tenant's burst past its bucket
        answers 429 + Retry-After (the PR-12 serve idiom) instead of
        letting one tenant's create storm starve the store's write path.
        ``None`` disables (the local/dev default); the standalone server
        exposes it as ``--rate-limit``."""
        self.store = store
        self.artifacts_root = os.path.abspath(artifacts_root)
        os.makedirs(self.artifacts_root, exist_ok=True)
        self.rate_limiter = None
        if rate_limit:
            from ..tenancy import TenantRateLimiter

            self.rate_limiter = TenantRateLimiter(
                rate=float(rate_limit), burst=rate_limit_burst)
        # the family is contracted (EXPECTED_FAMILIES) and must exist on
        # a server with rate limiting off too — registered from birth
        self.store.metrics.counter(
            "polyaxon_api_rate_limited_total",
            "API write requests shed by the per-tenant token bucket (429)",
            labels={"tenant": "default"})
        # Token auth (SURVEY.md §2 API "RBAC(-lite)"): auth engages when a
        # static admin token is configured OR the store holds minted tokens.
        # The static token is the admin bootstrap; store tokens (POST
        # /api/v1/tokens) are per-project capabilities — a scoped token
        # reaching another project's endpoints gets 403, not data.
        # No tokens anywhere = open (local dev).
        self.auth_token = auth_token if auth_token is not None \
            else os.environ.get("PLX_AUTH_TOKEN")
        self._tokens_seen = False
        # metrics history (ISSUE 20): the server process is long-lived,
        # so it starts the registry recorder's sampler thread (Stores
        # create the recorder idle — unit tests stay thread-free). The
        # history endpoint and the SLO status handler both read it.
        from ..obs.history import recorder_for
        from ..obs.slo import default_slo_pack

        self.recorder = recorder_for(
            self.store.metrics,
            interval_s=getattr(store, "record_interval_s", 10.0))
        self.slo_specs = default_slo_pack()
        # /metrics render cache (ISSUE 20 satellite): the recorder, the
        # dashboard poll, and external scrapers each re-rendered the
        # exposition per request — same registry lock, same string build.
        # One render per min(1s, record_interval_s) serves all three.
        self._scrape_ttl = min(1.0, self.recorder.interval_s)
        self._scrape_cache: tuple = (float("-inf"), "")
        self.app = web.Application(
            middlewares=[*(extra_middlewares or []), self._auth_middleware,
                         self._rate_limit_middleware,
                         self._conflict_middleware])
        # live push (ISSUE 14): one hub task tails the store's changelog
        # and fans run deltas to the SSE watchers of /api/v1/streams/runs;
        # constructed here (not at startup) so its polyaxon_stream_*
        # families are registered from birth, started on the app's loop
        from .stream import StreamHub

        self.stream = StreamHub(store)
        self.app.on_startup.append(self._start_stream)
        # on_shutdown, NOT on_cleanup: aiohttp waits for open handlers
        # BETWEEN the two, and the SSE handlers only exit once the hub's
        # stop evicts them — on_cleanup would deadlock the drain against
        # the watchers it is supposed to release
        self.app.on_shutdown.append(self._stop_stream)
        self._routes()
        # the scheduler (if attached in-process) watches this queue
        self.new_run_event = asyncio.Event()

    async def _start_stream(self, _app) -> None:
        await self.stream.start()

    async def _stop_stream(self, _app) -> None:
        await self.stream.stop()

    def _auth_enabled(self) -> bool:
        if self.auth_token:
            return True
        # sticky: once tokens exist auth stays on for this process (even if
        # all are later revoked — fail closed), and the hot path stops
        # paying a per-request DB probe
        if not self._tokens_seen:
            self._tokens_seen = self.store.has_tokens()
        return self._tokens_seen

    @web.middleware
    async def _auth_middleware(self, request, handler):
        # the static dashboard shell carries no data; the shell collects
        # the token client-side and sends it on its API calls. The OpenAPI
        # descriptor sits BEHIND auth (ADVICE r4): it carries no tenant
        # data either, but enumerating every route + summary is
        # reconnaissance surface, and SDK generators already hold a token.
        # /metrics joins the unauthenticated set deliberately: Prometheus
        # scrapers don't carry tenant tokens, and the exposition is
        # aggregate operational data (counters/latencies), never run
        # payloads (docs/OBSERVABILITY.md "Scraping")
        if request.path in ("/healthz", "/", "/ui", "/metrics"):
            return await handler(request)
        if not self._auth_enabled():
            return await handler(request)
        header = request.headers.get("Authorization", "")
        token = header[7:] if header.startswith("Bearer ") else None
        if token is None and request.path.startswith("/api/v1/streams/"):
            # EventSource cannot set request headers: the SSE stream
            # accepts the bearer token as ?access_token= (this path
            # only — everything else keeps the header-only contract)
            token = request.rel_url.query.get("access_token") or None
        if token is None:
            return _json({"error": "unauthorized"}, status=401)
        if self.auth_token and token == self.auth_token:
            request["identity"] = "admin"  # static admin token
            return await handler(request)
        row = self.store.resolve_token(token)
        if row is None:
            return _json({"error": "unauthorized"}, status=401)
        # run ownership (SURVEY.md:104 RBAC-lite): the token identity
        # stamps created_by on runs created through this request. Derived
        # from the STABLE token id — labels are user-chosen and non-unique,
        # so two tokens labelled "ci" must not share an identity (ADVICE
        # r5); the label rides along for display.
        label = row.get("label")
        request["identity"] = (
            f"{label}#{row['id']}" if label else f"token-{row['id']}")
        if row["project"] is None:
            return await handler(request)  # minted admin token
        # project-scoped: only that project's routes; token admin and
        # project creation stay admin-only
        path_project = request.match_info.get("project")
        if request.path.startswith("/api/v1/streams/"):
            # the stream endpoint is global by shape; a scoped token
            # subscribes fine but the hub pins its filter to the token's
            # project — other tenants' deltas never reach it
            request["scope_project"] = row["project"]
            return await handler(request)
        if request.path.startswith("/api/v1/tokens") or (
                path_project is None and request.path != "/api/v1/projects"):
            return _json({"error": "forbidden"}, status=403)
        if request.path == "/api/v1/projects":
            if request.method != "GET":
                return _json({"error": "forbidden"}, status=403)
            # the listing is visible but filtered to the token's project —
            # other tenants' names/descriptions are data too
            request["scope_project"] = row["project"]
        elif path_project != row["project"]:
            return _json({"error": "forbidden",
                          "detail": f"token is scoped to project "
                                    f"{row['project']!r}"}, status=403)
        return await handler(request)

    @web.middleware
    async def _rate_limit_middleware(self, request, handler):
        """Per-tenant token-bucket admission on the API write path
        (ISSUE 15 tentpole (2), PR-12 idiom). Runs AFTER auth, so the
        bucket keys on the token-derived tenant — one tenant's 10k-run
        create burst drains ITS bucket, not the fleet's. Reads are never
        limited (dashboards poll), and over-limit writes are shed with
        429 + Retry-After: the client backs off, nothing queues
        unbounded, nothing is silently dropped."""
        if (self.rate_limiter is None
                or request.method not in ("POST", "PUT", "DELETE")
                or not request.path.startswith("/api/v1/")):
            return await handler(request)
        from ..tenancy import tenant_of

        tenant = tenant_of(request.get("identity"))
        ok, retry_after = self.rate_limiter.acquire(tenant)
        if ok:
            return await handler(request)
        self.store.metrics.counter(
            "polyaxon_api_rate_limited_total",
            "API write requests shed by the per-tenant token bucket (429)",
            labels={"tenant": tenant}).inc()
        import math

        return _json(
            {"error": "rate limited",
             "detail": f"tenant {tenant!r} exceeded the API write rate "
                       f"({self.rate_limiter.rate:g}/s)",
             "tenant": tenant,
             "retry_after_s": round(retry_after, 3)},
            status=429,
            headers={"Retry-After": str(max(1, math.ceil(retry_after)))})

    @web.middleware
    async def _conflict_middleware(self, request, handler):
        """Store-state verdicts become their contracted HTTP answers
        (docs/RESILIENCE.md "Store crash matrix"):

        - fencing conflict -> 409 (the writer is stale — demote, never
          retry; only reachable when an embedder serves a write-fenced
          store: the plain API's own writes are unfenced by design)
        - epoch fence -> 410 (the ``?since=`` cursor predates a failover —
          the consumer must full-resync, never re-poll)
        - read-only / disk-full degraded store -> 503 + Retry-After (the
          client rotates to the next endpoint or waits; never a crash
          loop)"""
        from .store import StaleEpochError, StaleLeaseError, StoreReadOnlyError

        try:
            return await handler(request)
        except StaleLeaseError as e:
            return _json({"error": "stale lease", "detail": str(e)},
                         status=409)
        except StaleEpochError as e:
            return _json({"error": "stale epoch", "detail": str(e),
                          "epoch": e.current}, status=410)
        except StoreReadOnlyError as e:
            return _json({"error": "store unavailable", "detail": str(e)},
                         status=503, headers={"Retry-After": "2"})

    def run_dir(self, project: str, uuid: str) -> str:
        return run_artifacts_dir(self.artifacts_root, project, uuid)

    def _routes(self) -> None:
        r = self.app.router
        r.add_get("/healthz", self.healthz)
        r.add_get("/metrics", self.metrics_endpoint)
        r.add_get("/api/v1/stats", self.get_stats)
        r.add_get("/api/v1/metrics/history", self.metrics_history)
        r.add_get("/api/v1/alerts", self.list_alerts)
        r.add_get("/api/v1/slo/status", self.slo_status_endpoint)
        r.add_get("/", self.ui)
        r.add_get("/ui", self.ui)
        r.add_get("/api/v1/openapi.json", self.openapi)
        r.add_get("/api/v1/projects", self.list_projects)
        r.add_post("/api/v1/projects", self.create_project)
        r.add_post("/api/v1/tokens", self.create_token)
        r.add_get("/api/v1/tokens", self.list_tokens)
        r.add_delete("/api/v1/tokens/{token_id}", self.revoke_token)
        r.add_get("/api/v1/projects/{project}", self.get_project)
        r.add_get("/api/v1/quotas", self.list_quotas)
        r.add_get("/api/v1/quotas/{tenant}", self.get_quota)
        r.add_put("/api/v1/quotas/{tenant}", self.put_quota)
        r.add_delete("/api/v1/quotas/{tenant}", self.delete_quota)
        r.add_get("/api/v1/clusters", self.list_clusters)
        r.add_get("/api/v1/clusters/{name}", self.get_cluster)
        r.add_put("/api/v1/clusters/{name}", self.put_cluster)
        r.add_delete("/api/v1/clusters/{name}", self.delete_cluster)
        r.add_get("/api/v1/agent/lease", self.get_agent_lease)
        r.add_get("/api/v1/store", self.get_store_status)
        r.add_get("/api/v1/changelog", self.get_changelog)
        r.add_get("/api/v1/store/snapshot", self.get_snapshot)
        r.add_get("/api/v1/streams/runs", self.stream_runs)
        r.add_post("/api/v1/{project}/runs", self.create_run)
        r.add_get("/api/v1/{project}/runs", self.list_runs)
        r.add_get("/api/v1/{project}/runs/{uuid}", self.get_run)
        r.add_delete("/api/v1/{project}/runs/{uuid}", self.delete_run)
        r.add_post("/api/v1/{project}/runs/{uuid}/statuses", self.post_status)
        r.add_get("/api/v1/{project}/runs/{uuid}/statuses", self.get_statuses)
        r.add_post("/api/v1/{project}/runs/{uuid}/outputs", self.post_outputs)
        r.add_post("/api/v1/{project}/runs/{uuid}/heartbeat", self.post_heartbeat)
        r.add_post("/api/v1/{project}/runs/{uuid}/stop", self.stop_run)
        r.add_post("/api/v1/{project}/runs/{uuid}/restart", self.restart_run)
        r.add_get("/api/v1/{project}/runs/{uuid}/timeline", self.get_timeline)
        r.add_get("/api/v1/{project}/runs/{uuid}/metrics", self.get_metrics)
        r.add_get("/api/v1/{project}/runs/{uuid}/events/{kind}", self.get_events)
        r.add_get("/api/v1/{project}/runs/{uuid}/logs", self.get_logs)
        r.add_get("/api/v1/{project}/runs/{uuid}/artifacts/tree", self.artifacts_tree)
        r.add_get("/api/v1/{project}/runs/{uuid}/artifacts/file", self.artifacts_file)
        r.add_post("/api/v1/{project}/runs/{uuid}/lineage", self.post_lineage)
        r.add_get("/api/v1/{project}/runs/{uuid}/lineage", self.get_lineage)
        r.add_get("/api/v1/{project}/runs/{uuid}/portforward", self.portforward)

    # -- handlers ----------------------------------------------------------

    async def healthz(self, request):
        return _json({"status": "ok"})

    async def metrics_endpoint(self, request):
        """Prometheus text exposition of the control-plane registry
        (store counters + latency histograms, agent gauges, reaper/chaos
        counters — docs/OBSERVABILITY.md lists every family).

        The encoded text is cached for ``min(1s, record_interval_s)``
        (ISSUE 20): the recorder's sampler, the dashboard poll, and
        external scrapers would otherwise each pay the registry lock and
        the full string build per tick. A sub-TTL scrape may read a
        render up to one interval old — within the recorder's own
        resolution, so nothing downstream can tell."""
        ts, text = self._scrape_cache
        now = time.monotonic()
        if now - ts >= self._scrape_ttl:
            reg = getattr(self.store, "metrics", None)
            text = reg.render() if reg is not None else ""
            self._scrape_cache = (now, text)
        return web.Response(
            text=text,
            content_type="text/plain",
            charset="utf-8",
            headers={"X-Prometheus-Exposition": "0.0.4"},
        )

    async def metrics_history(self, request):
        """Ring-buffer history for one family (ISSUE 20): ``?family=``
        (required), ``?range=`` seconds (default 3600), ``?at=`` lookback
        seconds (history as it stood ``at`` seconds ago). Points are
        ``[age_s, value]`` pairs, oldest first; the ``series`` list keeps
        each reporter's labels + source, ``points`` is the fleet aggregate
        (sum counters / max gauges — the shared-registry rule)."""
        family = request.rel_url.query.get("family")
        if not family:
            return _json(
                {"error": "family query parameter is required",
                 "families": self.recorder.families()}, status=400)
        try:
            range_s = float(request.rel_url.query.get("range", 3600))
            at = float(request.rel_url.query.get("at", 0))
        except ValueError:
            return _json({"error": "range/at must be numbers"}, status=400)
        q = self.recorder.query(family, range_s, at=at)
        if not q["series"]:
            # empty is only a valid answer for a family the recorder COULD
            # serve (allowlisted or registered, just not sampled yet —
            # first tick lands interval_s after boot); anything else 404s
            # with the recordable set so a typo'd dashboard query is loud
            allow = self.recorder.allow
            reg = getattr(self.store, "metrics", None)
            known = ((allow is not None and family in allow)
                     or (reg is not None and family in reg.families()))
            if not known:
                return _json(
                    {"error": f"unknown family: {family}",
                     "families": sorted(allow or self.recorder.families())},
                    status=404)
        return _json(q)

    async def list_alerts(self, request):
        """The alert table (``?state=`` filters), firing-first — the
        dashboard panel's source and ``polyaxon alerts ls``."""
        state = request.rel_url.query.get("state") or None
        return _json({"alerts": self.store.list_alerts(state=state)})

    async def slo_status_endpoint(self, request):
        """Burn rates for the server's spec pack, computed by the SAME
        ``slo_status`` the evaluator and the CLI use."""
        from ..obs.slo import slo_status

        return _json({"slos": slo_status(self.recorder, self.slo_specs)})

    async def get_stats(self, request):
        """JSON twin of /metrics: store counters, metric snapshot
        (histograms as exact p50/p95), the scheduler lease state, and the
        sharded control plane's ownership table (ISSUE 6): every work
        lease row plus {holder: [shards]} for the live owners."""
        reg = getattr(self.store, "metrics", None)
        lease = None
        try:
            lease = self.store.get_lease(
                request.query.get("lease", "scheduler"))
        except Exception:
            pass
        shards, owners = [], {}
        try:
            from .store import shard_ownership

            shards, owners = shard_ownership(self.store.list_leases())
        except Exception:
            pass
        return _json({
            "store": dict(getattr(self.store, "stats", {}) or {}),
            "metrics": reg.snapshot() if reg is not None else {},
            "lease": lease,
            "shards": shards,
            "shard_owners": owners,
            # store survivability state (ISSUE 7): which epoch this
            # control plane is on and whether it is write-able right now
            "store_state": {
                "epoch": self.store.current_epoch(),
                "read_only": bool(getattr(self.store, "read_only", False)),
                "degraded": getattr(self.store, "degraded", None),
                # sharded backend (ISSUE 18): 0 = single-file store
                "store_num_shards": int(getattr(
                    self.store, "store_num_shards", 0) or 0),
            },
        })

    def _quota_in_use(self, tenant: str) -> float:
        """Live chips-in-use for a tenant, read from the shared registry
        (the agent binds polyaxon_tenant_chips_in_use{tenant} there) — no
        second accounting path for the quotas API to drift from."""
        g = self.store.metrics.get("polyaxon_tenant_chips_in_use",
                                   {"tenant": tenant})
        try:
            return float(g.value) if g is not None else 0.0
        except Exception:
            return 0.0

    async def list_quotas(self, request):
        """List tenant quotas with live usage (admin-only by scoping —
        the route carries no {project}, so scoped tokens get 403)."""
        rows = self.store.list_quotas()
        for row in rows:
            row["in_use"] = self._quota_in_use(row["tenant"])
        return _json(rows)

    async def get_quota(self, request):
        """One tenant's quota + live usage."""
        tenant = request.match_info["tenant"]
        row = self.store.get_quota(tenant)
        if row is None:
            return _not_found(f"tenant {tenant!r} has no quota")
        row["in_use"] = self._quota_in_use(tenant)
        return _json(row)

    async def put_quota(self, request):
        """Set a tenant's chip quota: body {"chips": N} (admin-only)."""
        tenant = request.match_info["tenant"]
        body = await request.json()
        try:
            chips = int(body["chips"])
            if chips < 0:
                raise ValueError
        except (KeyError, TypeError, ValueError):
            return _json({"error": "body must carry a non-negative "
                                   "integer 'chips'"}, status=400)
        return _json(self.store.set_quota(tenant, chips), 201)

    async def delete_quota(self, request):
        """Drop a tenant's quota row (in-flight runs fall back to the
        default quota loudly — docs/SCHEDULING.md)."""
        ok = self.store.delete_quota(request.match_info["tenant"])
        return _json({"deleted": ok}, 200 if ok else 404)

    async def list_clusters(self, request):
        """The federated cluster registry with live health (ISSUE 16):
        each row carries region/chip_type/registered capacity plus a
        ``healthy`` flag computed from its cluster-health TTL lease.
        Admin-only by scoping (no {project} in the route)."""
        return _json(self.store.list_clusters())

    async def get_cluster(self, request):
        name = request.match_info["name"]
        row = self.store.get_cluster(name)
        if row is None:
            return _not_found(f"cluster {name!r} is not registered")
        return _json(row)

    async def put_cluster(self, request):
        """Register/update a cluster backend out-of-band (agents register
        themselves at start; this is the operator path for pre-seeding a
        registry or correcting capacity). Body: {"region", "chipType",
        "capacity"} — all optional."""
        name = request.match_info["name"]
        body = await request.json()
        try:
            capacity = int(body.get("capacity", 0) or 0)
            if capacity < 0:
                raise ValueError
        except (TypeError, ValueError):
            return _json({"error": "'capacity' must be a non-negative "
                                   "integer"}, status=400)
        return _json(self.store.register_cluster(
            name, region=body.get("region"),
            chip_type=body.get("chipType", body.get("chip_type")),
            capacity=capacity), 201)

    async def delete_cluster(self, request):
        """The DEATH CERTIFICATE (docs/RESILIENCE.md "Cluster crash
        matrix"): the operator's assertion that this cluster — and every
        pod on it — is permanently gone. Survivor agents then re-place
        its remaining runs WITHOUT proving the pod set is dead first, so
        only issue it when the hardware truly is."""
        ok = self.store.delete_cluster(request.match_info["name"])
        return _json({"deleted": ok}, 200 if ok else 404)

    async def get_timeline(self, request):
        """The run's merged trace: control-plane lifecycle spans (from the
        transactionally-stamped status conditions) + pod-side spans logged
        through tracking — the waterfall the dashboard Timeline tab and
        `polyaxon timeline` render."""
        run = self._run(request)
        if run is None:
            return _not_found()
        from ..obs.trace import build_timeline

        rd = self.run_dir(run["project"], run["uuid"])
        conditions = self.store.get_statuses(run["uuid"])
        return _json(build_timeline(run, conditions, rd))

    async def get_agent_lease(self, request):
        """Who drives the control plane right now (admin-only by scoping:
        the route carries no {project}). ``lease: null`` = no live agent —
        either none started, or the holder crashed and its TTL has not
        expired yet (``expired: true`` on the row when it has)."""
        name = request.query.get("name", "scheduler")
        return _json({"lease": self.store.get_lease(name)})

    async def get_store_status(self, request):
        """Store survivability state: epoch, committed seq, read-only /
        degraded flags (admin-only by scoping, like /agent/lease)."""
        span = {}
        try:
            span = self.store.changelog_span()
        except Exception:
            pass
        return _json({
            "epoch": self.store.current_epoch(),
            "seq": self.store.current_seq(),
            "changelog_seq": span.get("seq"),
            "read_only": bool(getattr(self.store, "read_only", False)),
            "degraded": getattr(self.store, "degraded", None),
        })

    async def get_changelog(self, request):
        """Replication tail: commit-ordered changelog rows after ?after=
        (admin-only — row deltas span every project). A standby server
        polls this; docs/RESILIENCE.md 'Running a warm standby'."""
        q = request.rel_url.query
        from .store import CompactedLogError

        try:
            after = int(q.get("after", 0))
            limit = min(int(q.get("limit", 500)), 2000)
        except ValueError:
            return _json({"error": "after/limit must be integers"},
                         status=400)
        span = self.store.changelog_span()
        try:
            rows = self.store.get_changelog(after, limit)
        except CompactedLogError as e:
            # the tailer's cursor predates the compaction floor: 410 so
            # it re-bootstraps from the snapshot instead of silently
            # skipping the pruned writes
            return _json({"error": "changelog compacted",
                          "detail": str(e), "floor": e.floor}, status=410)
        return _json({"rows": rows,
                      "seq": span["seq"], "epoch": span["epoch"]})

    async def stream_runs(self, request):
        """SSE change-feed subscription (ISSUE 14): live run deltas off
        the commit-ordered changelog — ``event: run|delete|heartbeat``
        frames whose ``id:`` is the feed token, so ``Last-Event-ID``
        reconnects resume loss-free; 410 on a pre-failover or compacted
        token (full resync), 503 + Retry-After past ``max_watchers``.
        ``?project=`` filters; ``?access_token=`` carries auth for
        EventSource clients (docs/OBSERVABILITY.md "Live streams")."""
        return await self.stream.handle(request)

    async def get_snapshot(self, request):
        """Crash-consistent store snapshot (standby bootstrap): streams
        snapshot.db with its sha256/seq/epoch manifest in headers.
        Against a sharded store (ISSUE 18) pass ``?shard=i`` to stream
        shard i's snapshot.db; omitting it is a 400 carrying
        ``num_shards`` so the client can iterate — there is no single
        whole-fleet DB file to stream."""
        import shutil
        import time as _time
        import uuid as _uuid

        backends = getattr(self.store, "backends", None)
        snap_store = self.store
        if backends is not None:
            raw = request.rel_url.query.get("shard")
            if raw is None:
                return _json(
                    {"error": "sharded store: pass ?shard=i",
                     "num_shards": len(backends)}, status=400)
            try:
                idx = int(raw)
                snap_store = backends[idx] if idx >= 0 else None
            except (ValueError, IndexError):
                snap_store = None
            if snap_store is None:
                return _json(
                    {"error": f"shard {raw!r} out of range",
                     "num_shards": len(backends)}, status=400)

        # per-request dir: two concurrent bootstraps must not race one
        # shared snapshot.db (headers from one body from the other);
        # older request dirs are garbage-collected best-effort
        root = os.path.join(self.artifacts_root, ".snapshots")
        snap_dir = os.path.join(root, _uuid.uuid4().hex[:12])

        def _make() -> dict:
            os.makedirs(root, exist_ok=True)
            for entry in os.listdir(root):
                p = os.path.join(root, entry)
                try:
                    # plx: allow(clock): compared against file MTIMES, which are wall-clock by definition
                    if _time.time() - os.path.getmtime(p) > 3600:
                        shutil.rmtree(p, ignore_errors=True)
                except OSError:
                    pass
            return snap_store.snapshot(snap_dir)

        # off the event loop: the backup+sha256 is O(whole DB), and
        # stalling the loop for it would silence /api/v1/changelog long
        # enough to trip an attached standby's promote-on-silence rule
        manifest = await asyncio.get_event_loop().run_in_executor(
            None, _make)
        return web.FileResponse(
            os.path.join(snap_dir, "snapshot.db"),
            headers={
                "X-Snapshot-Sha256": manifest["sha256"],
                "X-Snapshot-Seq": str(manifest["seq"]),
                "X-Snapshot-Epoch": str(manifest["epoch"]),
                "X-Snapshot-Created-At": manifest["created_at"],
                "Content-Type": "application/octet-stream",
            })

    async def ui(self, request):
        from .ui import UI_HTML

        return web.Response(text=UI_HTML, content_type="text/html")

    async def openapi(self, request):
        """Machine-readable API descriptor (upstream shipped a ~25k-LoC
        generated OpenAPI SDK, SURVEY.md §2 Client row; here the spec is
        derived from the live route table — handler docstrings become the
        operation summaries, clients generate from /api/v1/openapi.json)."""
        paths: dict = {}
        for route in self.app.router.routes():
            method = route.method.lower()
            if method == "head":
                continue
            info = route.resource.get_info() if route.resource else {}
            path = info.get("path") or info.get("formatter")
            if not path or not path.startswith("/api/"):
                continue
            doc = (route.handler.__doc__ or "").strip().split("\n")[0]
            entry = {
                "summary": doc or route.handler.__name__,
                "responses": {"200": {"description": "OK"}},
            }
            params = [
                {"name": name, "in": "path", "required": True,
                 "schema": {"type": "string"}}
                for name in _PATH_PARAM_RE.findall(path)
            ]
            if params:
                entry["parameters"] = params
            paths.setdefault(path, {})[method] = entry
        return _json({
            "openapi": "3.0.3",
            "info": {"title": "polyaxon_tpu API", "version": "0.1.0"},
            "components": {"securitySchemes": {
                "bearer": {"type": "http", "scheme": "bearer"}}},
            "security": [{"bearer": []}],
            "paths": dict(sorted(paths.items())),
        })

    async def list_projects(self, request):
        """List projects (scoped tokens see only their own)."""
        projects = self.store.list_projects()
        scope = request.get("scope_project")
        if scope is not None:
            projects = [p for p in projects if p["name"] == scope]
        return _json(projects)

    async def create_token(self, request):
        """Mint an access token: admin, or scoped to one project."""
        # minting over the network requires an authenticated caller: on an
        # open server an anonymous first mint would flip auth ON with the
        # attacker holding the only admin credential (review r4 finding).
        # Bootstrap is the --auth-token flag or the local hostless CLI.
        if not self._auth_enabled():
            return _json(
                {"error": "token minting needs auth bootstrap: start the "
                          "server with --auth-token, or mint locally with "
                          "`polyaxon_tpu token create` (no --host)"},
                status=403)
        body = await request.json() if request.can_read_body else {}
        out = self.store.create_token(
            project=body.get("project"), label=body.get("label"))
        self._tokens_seen = True
        return _json(out, 201)

    async def list_tokens(self, request):
        """List token metadata (never raw tokens)."""
        return _json(self.store.list_tokens())

    async def revoke_token(self, request):
        """Revoke a token by id."""
        try:
            tid = int(request.match_info["token_id"])
        except ValueError:
            return _not_found("token id must be an integer")
        ok = self.store.revoke_token(tid)
        return _json({"revoked": ok}) if ok else _not_found()

    async def create_project(self, request):
        """Create a project (idempotent)."""
        body = await request.json()
        return _json(self.store.create_project(body["name"], body.get("description")), 201)

    async def get_project(self, request):
        """Fetch one project."""
        p = self.store.get_project(request.match_info["project"])
        return _json(p) if p else _not_found()

    async def create_run(self, request):
        """Create a run from an operation spec body."""
        project = request.match_info["project"]
        body = await request.json()
        meta = body.get("meta")
        if isinstance(meta, dict):
            # meta["service"] is the agent-stamped portforward endpoint —
            # honoring a client-supplied value would let a tenant point the
            # server's TCP bridge at ANY host:port it can reach (SSRF,
            # ADVICE r5 high). Only the agent writes it, via the store.
            meta = {k: v for k, v in meta.items() if k != "service"}
        # tenant (ISSUE 15): derived server-side from the token identity;
        # an explicit body tenant is honored only for admin/auth-off
        # callers — a scoped token must not bill another tenant's quota
        identity = request.get("identity")
        tenant = body.get("tenant")
        if tenant is not None and identity not in (None, "admin"):
            tenant = None
        run = self.store.create_run(
            project,
            spec=body.get("spec"),
            name=body.get("name"),
            kind=body.get("kind"),
            inputs=body.get("inputs"),
            meta=meta,
            tags=body.get("tags"),
            pipeline_uuid=body.get("pipeline_uuid"),
            # server-derived from the auth token, never client-supplied
            created_by=identity,
            tenant=tenant,
        )
        self.new_run_event.set()
        return _json(run, 201)

    async def list_runs(self, request):
        """List runs (?status=&limit=&offset=; ?paged=1 / ?cursor= /
        ?since= return {results, count, next_cursor, server_time})."""
        q = request.rel_url.query
        filters = dict(
            project=request.match_info["project"],
            status=q.get("status"),
            pipeline_uuid=q.get("pipeline_uuid"),
            created_by=q.get("created_by"),
        )
        limit = int(q.get("limit", 100))
        since, cursor = q.get("since"), q.get("cursor")
        paged = q.get("paged") not in (None, "", "0")
        if since is None and cursor is None and not paged:
            # legacy shape: a bare JSON list
            return _json(self.store.list_runs(
                **filters, limit=limit, offset=int(q.get("offset", 0))))
        # envelope shape (VERDICT r5 weak #7): keyset pagination means a
        # deep page is O(page), and ?since= lets pollers fetch only the
        # rows that changed — O(delta) instead of O(all-runs) every 4s.
        if since is not None and cursor is not None:
            # ambiguous: a delta poll with a stale cursor attached would
            # consume rows but get no resume token back
            return _json({"error": "cursor and since are mutually "
                                   "exclusive"}, status=400)
        if since is not None and not re.fullmatch(r"-?\d+(:-?\d+)?", since):
            return _json({"error": f"invalid since token {since!r} "
                                   "(expected a change_seq int, optionally "
                                   "epoch-qualified as epoch:seq)"},
                         status=400)
        # bootstrap token: the latest COMMITTED change_seq, read BEFORE the
        # SELECT — an in-flight writer's bump is invisible until its
        # commit, so its rows always sort after this token and the next
        # delta poll delivers them (loss-free, at worst a duplicate).
        # Epoch-qualified (ISSUE 7): a token outliving a store failover is
        # rejected with 410 instead of silently skipping the rows lost in
        # the replication-lag window.
        server_time = self.store.feed_token(self.store.current_seq())
        # fetch one extra row to learn whether a further page exists —
        # an exactly-full last page must not hand out a dangling cursor
        rows = self.store.list_runs(
            **filters, limit=limit + 1, cursor=cursor, since=since)
        has_more = len(rows) > limit
        rows = rows[:limit]
        next_cursor = None
        if since is None and has_more:
            next_cursor = self.store.run_cursor(rows[-1])
        if since is not None:
            # delta polls resume exactly after the LAST DELIVERED row (a
            # truncated page walks the remainder instead of losing it);
            # an empty delta echoes the caller's token back unchanged
            server_time = (self.store.since_token(rows[-1]) if rows
                           else since)
        if cursor is not None:
            # continuation pages: no bootstrap token (a run created during
            # a multi-page DESC walk never appears on LATER pages, so only
            # the FIRST page's token is a loss-free since bootstrap) and
            # no COUNT(*) re-scan (the total is identical across the walk;
            # the first page already carried it)
            server_time = None
        return _json({
            "results": rows,
            # the COUNT(*) is for pagination UIs; delta polls and cursor
            # continuations don't need it and must stay O(delta)/O(page)
            "count": (self.store.count_runs(**filters)
                      if since is None and cursor is None else None),
            "next_cursor": next_cursor,
            # clients echo this back as the next ?since= — an opaque
            # commit-ordered token, no clock-skew games
            "server_time": server_time,
        })

    def _run(self, request) -> Optional[dict]:
        return self.store.get_run(request.match_info["uuid"])

    async def get_run(self, request):
        """Fetch one run row."""
        run = self._run(request)
        return _json(run) if run else _not_found()

    async def delete_run(self, request):
        """Delete a run and its artifacts."""
        ok = self.store.delete_run(request.match_info["uuid"])
        return _json({"deleted": ok}, 200 if ok else 404)

    async def post_status(self, request):
        """Apply a status transition {status, reason?, message?}."""
        body = await request.json()
        run, changed = self.store.transition(
            request.match_info["uuid"], body["status"],
            reason=body.get("reason"), message=body.get("message"),
            force=bool(body.get("force")),
        )
        if run is None:
            return _not_found()
        return _json({"run": run, "changed": changed})

    async def get_statuses(self, request):
        """Status condition history for a run."""
        run = self._run(request)
        if run is None:
            return _not_found()
        return _json({"status": run["status"],
                      "conditions": self.store.get_statuses(run["uuid"])})

    async def post_outputs(self, request):
        """Merge a dict into run.outputs."""
        body = await request.json()
        run = self.store.merge_outputs(request.match_info["uuid"], body)
        return _json(run) if run else _not_found()

    async def post_heartbeat(self, request):
        """Renew the run's liveness lease (zombie-reaper input). Optional
        JSON body {step, anomalies, rollbacks} carries the pod's training
        progress + cumulative divergence-guard counters (ISSUE 8)."""
        body = {}
        try:
            body = await request.json()
        except Exception:
            pass  # bodyless beats stay legal (pre-r9 pods, curl probes)
        if not isinstance(body, dict):
            body = {}

        def _int(v):
            try:
                return int(v)
            except (TypeError, ValueError):
                return None

        anomalies = body.get("anomalies")
        if isinstance(anomalies, dict):
            anomalies = {str(k): n for k, v in anomalies.items()
                         if (n := _int(v)) is not None}
        else:
            anomalies = None
        # malformed progress fields degrade to a liveness-only beat — a
        # buggy client must never get its heartbeat 500'd (and then
        # zombie-reaped) over a field the beat doesn't even need
        serve = body.get("serve")
        if not isinstance(serve, dict):
            serve = None  # malformed -> liveness-only, same as the rest
        metrics = body.get("metrics")
        if not isinstance(metrics, dict):
            metrics = None  # ISSUE 20 history buffer, same degrade rule
        ok = self.store.heartbeat(
            request.match_info["uuid"],
            step=_int(body.get("step")),
            anomalies=anomalies or None,
            rollbacks=_int(body.get("rollbacks")),
            incarnation=(str(body["incarnation"])
                         if body.get("incarnation") else None),
            serve=serve,
            metrics=metrics)
        return _json({"ok": True}) if ok else _not_found()

    async def stop_run(self, request):
        """Request the run stop (stopping -> stopped)."""
        run, changed = self.store.transition(
            request.match_info["uuid"], V1Statuses.STOPPING.value
        )
        if run is None:
            return _not_found()
        return _json({"run": run, "changed": changed})

    async def restart_run(self, request):
        """Clone-with-restart (upstream V1CloningKind.RESTART): new run, same
        spec, original's artifacts path wired in via meta for resume."""
        run = self._run(request)
        if run is None:
            return _not_found()
        body = {}
        try:
            body = await request.json()
        except Exception:
            pass
        meta = dict(run.get("meta") or {})
        # the clone's endpoint is stamped fresh by the agent when the clone
        # schedules; carrying the original's over would leave a stale (or
        # dead) portforward target on the new run
        meta.pop("service", None)
        meta["resume_from"] = self.run_dir(run["project"], run["uuid"])
        clone = self.store.create_run(
            run["project"],
            spec=body.get("spec") or run["spec"],
            name=run["name"],
            kind=run["kind"],
            inputs=run["inputs"],
            meta=meta,
            tags=run["tags"],
            original_uuid=run["uuid"],
            cloning_kind="restart",
            # the restarter owns the clone (review r5: a restarted run must
            # not fall out of `ops ls --created-by`)
            created_by=request.get("identity"),
        )
        self.new_run_event.set()
        return _json(clone, 201)

    async def get_metrics(self, request):
        """Metric events per name (?names=a,b)."""
        run = self._run(request)
        if run is None:
            return _not_found()
        rd = self.run_dir(run["project"], run["uuid"])
        names = request.rel_url.query.get("names")
        names = names.split(",") if names else list_event_names(rd, "metric")
        out = {
            n: [e.to_dict() for e in read_events(rd, "metric", n)] for n in names
        }
        return _json(out)

    async def get_events(self, request):
        """Events of any kind per name."""
        run = self._run(request)
        if run is None:
            return _not_found()
        kind = request.match_info["kind"]
        rd = self.run_dir(run["project"], run["uuid"])
        names = request.rel_url.query.get("names")
        names = names.split(",") if names else list_event_names(rd, kind)
        return _json({n: [e.to_dict() for e in read_events(rd, kind, n)] for n in names})

    async def portforward(self, request):
        """TCP-over-websocket bridge to a `kind: service` run (SURVEY.md:97
        `polyaxon port-forward`). The agent stamped where the service is
        reachable *from this server* into meta["service"] (loopback for
        local/FakeCluster pods, Service DNS under KubeCluster); the CLI
        bridges a local listening socket to this endpoint — an SSH-less
        TCP proxy through the agent, no SPDY required. Binary ws messages
        carry the byte stream in both directions; either side closing
        tears down the other."""
        run = self._run(request)
        if run is None:
            return _not_found()
        svc = (run.get("meta") or {}).get("service")
        if not svc:
            return _json(
                {"error": "run has no service endpoint (not a service "
                          "kind, or not scheduled yet)"}, status=409)
        raw_port = request.rel_url.query.get("port", svc["port"])
        try:
            port = int(raw_port)
        except (TypeError, ValueError):
            return _json({"error": f"invalid port {raw_port!r}"}, status=400)
        # only AGENT-STAMPED ports are reachable: the stamped host is the
        # server's own vantage point (loopback in local deployments), so a
        # free-form ?port= would be a bridge to every local daemon — and
        # the client-supplied spec is not trustworthy either (a tenant
        # could declare 22); the agent stamps the resolved declared ports
        # into meta["service"]["ports"] at schedule time
        declared = {int(svc["port"])}
        declared.update(int(p) for p in (svc.get("ports") or []))
        if port not in declared:
            # 404, not 403: from the caller's view an undeclared port
            # simply does not exist on this service — and the distinction
            # leaks nothing about what IS listening on the agent host
            return _json(
                {"error": f"port {port} is not a declared port of this "
                          f"service (declared: {sorted(declared)})"},
                status=404)
        ws = web.WebSocketResponse(max_msg_size=1 << 22)
        await ws.prepare(request)
        try:
            reader, writer = await asyncio.open_connection(svc["host"], port)
        except OSError as e:
            await ws.close(code=1011, message=str(e).encode()[:120])
            return ws

        async def to_target():
            async for msg in ws:
                if msg.type != web.WSMsgType.BINARY:
                    break
                if not msg.data:
                    # in-band EOF marker: the CLI's local client half-closed
                    # — forward the FIN, keep reading (ws stays open for
                    # the response direction)
                    if writer.can_write_eof():
                        writer.write_eof()
                    continue
                writer.write(msg.data)
                await writer.drain()

        async def to_client():
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    break
                await ws.send_bytes(data)
            await ws.close()

        tasks = [asyncio.ensure_future(to_target()),
                 asyncio.ensure_future(to_client())]
        try:
            await asyncio.wait(tasks, return_when=asyncio.FIRST_COMPLETED)
        finally:
            for t in tasks:
                t.cancel()
            # retrieve results so abrupt disconnects don't log
            # "Task exception was never retrieved" per dropped tunnel
            await asyncio.gather(*tasks, return_exceptions=True)
            writer.close()
        return ws

    async def get_logs(self, request):
        """Log text (?offset=N&tail=M; X-Log-Offset header)."""
        run = self._run(request)
        if run is None:
            return _not_found()
        rd = self.run_dir(run["project"], run["uuid"])
        logs_dir = os.path.join(rd, "logs")
        offset = int(request.rel_url.query.get("offset", 0))
        chunks = []
        if os.path.isdir(logs_dir):
            for f in sorted(os.listdir(logs_dir)):
                with open(os.path.join(logs_dir, f), encoding="utf-8") as fh:
                    chunks.append(fh.read())
        text = "".join(chunks)
        total = len(text)
        text = text[offset:]
        tail = request.rel_url.query.get("tail")
        if tail is not None:
            try:
                tail_n = int(tail)
            except ValueError:
                return _json({"error": f"invalid tail {tail!r}"}, status=400)
            if tail_n <= 0:
                text = ""
            else:
                lines = text.splitlines(keepends=True)
                text = "".join(lines[-tail_n:])
        return web.Response(
            text=text,
            headers={"X-Log-Offset": str(total)},
            content_type="text/plain",
        )

    def _safe_path(self, rd: str, rel: str) -> Optional[str]:
        # realpath on both sides so a symlink planted inside the run dir
        # cannot escape the artifacts root
        root = os.path.realpath(rd)
        p = os.path.realpath(os.path.join(rd, rel))
        if not (p + os.sep).startswith(root + os.sep) and p != root:
            return None
        return p

    async def artifacts_tree(self, request):
        """List an artifact directory (?path=)."""
        run = self._run(request)
        if run is None:
            return _not_found()
        rd = self.run_dir(run["project"], run["uuid"])
        rel = request.rel_url.query.get("path", "")
        p = self._safe_path(rd, rel)
        if p is None or not os.path.isdir(p):
            return _not_found("no such dir")
        files, dirs = [], []
        for entry in sorted(os.listdir(p)):
            full = os.path.join(p, entry)
            if os.path.isdir(full):
                dirs.append(entry)
            else:
                files.append({"name": entry, "size": os.path.getsize(full)})
        return _json({"path": rel, "dirs": dirs, "files": files})

    async def artifacts_file(self, request):
        """Download one artifact file (?path=)."""
        run = self._run(request)
        if run is None:
            return _not_found()
        rd = self.run_dir(run["project"], run["uuid"])
        rel = request.rel_url.query.get("path", "")
        p = self._safe_path(rd, rel)
        if p is None or not os.path.isfile(p):
            return _not_found("no such file")
        return web.FileResponse(p)

    async def post_lineage(self, request):
        """Record an artifact lineage entry."""
        run = self._run(request)
        if run is None:
            return _not_found()
        body = await request.json()
        self.store.add_lineage(run["uuid"], body)
        return _json({"ok": True}, 201)

    async def get_lineage(self, request):
        """Artifact lineage entries for a run."""
        run = self._run(request)
        if run is None:
            return _not_found()
        return _json(self.store.get_lineage(run["uuid"]))
