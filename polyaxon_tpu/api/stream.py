"""Live push control plane: SSE change-feed fan-out (ISSUE 14).

PR 3 built the commit-ordered ``?since=`` change feed and PR 7 gave it
exact failover semantics — this module finally serves it LIVE. One
:class:`StreamHub` task tails the store's changelog (the same
commit-ordered log replication rides, so one event per committed write —
nothing coalesces, nothing reorders) and fans deltas out to N subscribers
of ``GET /api/v1/streams/runs`` over per-watcher *bounded* queues.

Robustness contract (docs/RESILIENCE.md "Store crash matrix", watcher
row):

- **Slow watchers are evicted, never absorbed**: a watcher that can't
  drain its buffer gets an ``evicted`` control event and a close — it
  can NEVER backpressure the hub or starve other watchers. Every event
  carries its feed token as the SSE ``id:``, so the standard
  ``Last-Event-ID`` reconnect resumes exactly where the stream broke —
  loss-free, duplicate-free, no full re-list.
- **Failover-exact tokens**: a ``Last-Event-ID`` (or ``?since=``) from
  before a store failover answers a deterministic 410 (epoch fence), and
  one at or below the changelog compaction floor answers 410 too — the
  pruned range is gone, and serving only the survivors would silently
  diverge the watcher. 410 means *full resync*: re-list, then subscribe
  fresh. Mid-stream, an epoch change makes the hub broadcast a
  ``resync`` control event to every watcher for the same reason.
- **Bounded admission**: past ``max_watchers`` the endpoint sheds with
  503 + Retry-After (the PR-12 overload idiom) — a watcher burst
  degrades loudly instead of melting the event loop.
- **Async-correct** (analyzer rule R3): every store touch from the
  handler or the hub task runs in the default executor; the event loop
  only ever formats frames and awaits queues.

Event shapes (``data:`` is JSON):

- ``hello``      {since, epoch} — the subscriber's loss-free bootstrap
  token (list first, then trust deltas after this token)
- ``run``        a full client-shape run row (create/transition/output
  merge — one event per committed write, in commit order)
- ``delete``     {uuid}
- ``heartbeat``  {uuid, step?, at} — liveness/progress ticks; the
  dashboard uses them to refresh log tails and badges without polling
- ``evicted``    {reason} then close — reconnect with Last-Event-ID
- ``resync``     {epoch} then close — full resync, reconnect WITHOUT a
  token

Metrics (contracted in docs/OBSERVABILITY.md + test_obs):
``polyaxon_stream_watchers``, ``polyaxon_stream_events_total``,
``polyaxon_stream_evictions_total{reason}``,
``polyaxon_stream_rejected_total``.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web

from ..resilience.heartbeat import age_seconds
from .store import CompactedLogError, StaleEpochError, Store

#: ops forwarded to watchers — everything else in the changelog (lease,
#: intent, condition, config, token, lineage) is control-plane internals;
#: ``condition`` is deliberately skipped: the run row of the same
#: transition already carries the new status, on the same commit
_FORWARD_OPS = {"run", "delete_run", "heartbeat", "alert"}

#: eviction reasons (the {reason} label values of
#: polyaxon_stream_evictions_total)
EVICT_SLOW = "slow"
EVICT_RESYNC = "resync"
EVICT_WRITE_TIMEOUT = "write_timeout"


def _fmt_token(epoch: int, seq: int) -> str:
    """The SSE ``id:`` — byte-identical to Store.feed_token: bare seq at
    epoch 0 (pre-failover compatible), ``epoch:seq`` after a promotion."""
    return f"{epoch}:{seq}" if epoch else str(seq)


class _Watcher:
    __slots__ = ("queue", "project", "evicted", "reason")

    def __init__(self, buffer: int, project: Optional[str]):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=buffer)
        self.project = project
        self.evicted = False
        self.reason: Optional[str] = None


class _Ctl:
    """Control sentinel pushed into a watcher's queue (eviction/resync)."""

    __slots__ = ("reason",)

    def __init__(self, reason: str):
        self.reason = reason


class StreamHub:
    """One changelog tailer fanning run deltas to N SSE watchers.

    All hub state lives on the server's event loop: publication, (un)
    registration and eviction run as loop callbacks, so no locks — the
    only cross-thread entry is the store's transition listener, which
    sets the wake event via ``call_soon_threadsafe``. Store reads happen
    in the default executor (R3)."""

    def __init__(self, store: Store, *, max_watchers: int = 256,
                 buffer: int = 256, poll_interval: float = 0.5,
                 keepalive_s: float = 15.0, write_timeout_s: float = 10.0,
                 metrics=None):
        self.store = store
        self.max_watchers = int(max_watchers)
        #: per-watcher queue bound; a watcher further behind than this is
        #: evicted (it resumes by Last-Event-ID — cheap for it, free for
        #: everyone else)
        self.buffer = int(buffer)
        #: heartbeats don't fire transition listeners; the poll floor
        #: bounds their delivery latency (transitions wake instantly)
        self.poll_interval = float(poll_interval)
        self.keepalive_s = float(keepalive_s)
        #: a watcher whose TCP write can't complete within this is gone
        #: (kernel buffers full on a stalled peer) — closed and counted
        self.write_timeout_s = float(write_timeout_s)
        #: send-side buffering bound (bytes): applied to BOTH the asyncio
        #: transport high-water mark and the socket's SO_SNDBUF, so a
        #: consumer that stops draining backpressures the handler after
        #: ~this many bytes instead of after the kernel's auto-tuned
        #: megabytes — which is what makes a laggard's bounded queue
        #: actually fill. None (production default) leaves the kernel
        #: defaults; tests and the watcher-fault soak shrink it to make
        #: evictions deterministic at small event volumes.
        self.write_high_water: Optional[int] = None
        self._watchers: dict[int, _Watcher] = {}
        self._next_id = 0
        self._cursor = 0
        self._epoch = 0
        self._running = False
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # uuid -> project for heartbeat/delete scoping (run events carry
        # their own); misses resolved in the tail executor, pruned on
        # delete — bounded by live runs
        self._projects: dict[str, Optional[str]] = {}

        reg = metrics if metrics is not None else getattr(
            store, "metrics", None)
        if reg is None:
            from ..obs.metrics import MetricsRegistry

            reg = MetricsRegistry()
        self.metrics = reg
        self._g_watchers = reg.gauge(
            "polyaxon_stream_watchers",
            "Live SSE change-feed subscribers",
            value_fn=lambda: len(self._watchers))
        self._c_events = reg.counter(
            "polyaxon_stream_events_total",
            "Change-feed events published by the stream hub (per event, "
            "not per delivery)")
        self._c_evicted = {
            reason: reg.counter(
                "polyaxon_stream_evictions_total",
                "Watchers evicted from the SSE stream",
                labels={"reason": reason})
            for reason in (EVICT_SLOW, EVICT_RESYNC, EVICT_WRITE_TIMEOUT)}
        self._c_rejected = reg.counter(
            "polyaxon_stream_rejected_total",
            "Stream subscriptions shed at the max_watchers admission "
            "bound (503 + Retry-After)")

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._running:
            return
        self._loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        self._running = True
        self.store.add_transition_listener(self._on_transition)
        boot = await self._loop.run_in_executor(None, self._read_head)
        self._epoch, self._cursor = boot
        self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        self._running = False
        for w in list(self._watchers.values()):
            self._evict(w, EVICT_RESYNC, count=False)
        if self._wake is not None:
            self._wake.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    def _on_transition(self, _uuid: str, _status: str) -> None:
        # store writer threads -> loop wake; after stop() (or before
        # start) this is a no-op — listeners can't be unregistered
        loop, wake = self._loop, self._wake
        if not self._running or loop is None or wake is None:
            return
        try:
            loop.call_soon_threadsafe(wake.set)
        except RuntimeError:
            pass  # loop already closed (server teardown)

    # -- the tail task -----------------------------------------------------

    def _read_head(self) -> tuple[int, int]:
        """(epoch, latest committed seq) — the subscribe-from-now
        bootstrap. Runs in the executor. Store weather (SQLITE_BUSY
        burst, failover window) rides a short bounded retry: the tail
        loop treats every later read as retryable, and the one boot
        read must not be the single place a transient can kill server
        startup."""
        delay = 0.05
        for _ in range(5):
            try:
                return self.store.current_epoch(), self.store.current_seq()
            except Exception:
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
        return self.store.current_epoch(), self.store.current_seq()

    def _fetch(self) -> tuple[int, int, list[dict]]:
        """Changelog rows after the hub cursor (paged to exhaustion) plus
        the store's current epoch and the RAW tail cursor — the cursor
        must advance past skipped ops too (a page of pure lease renewals
        would otherwise be re-read forever). Runs in the executor."""
        epoch = self.store.current_epoch()
        rows: list[dict] = []
        cursor = self._cursor
        while True:
            page = self.store.get_changelog(cursor, 500)
            if not page:
                break
            rows.extend(page)
            cursor = page[-1]["seq"]
            if len(page) < 500:
                break
        return epoch, cursor, self._to_events(rows)

    def _to_events(self, rows: list[dict]) -> list[dict]:
        """Changelog rows -> watcher events (sync; executor context, so
        heartbeat project-cache misses may read the store)."""
        out = []
        for rec in rows:
            op = rec["op"]
            if op not in _FORWARD_OPS:
                continue
            seq, epoch, payload = rec["seq"], int(rec["epoch"]), rec["payload"]
            if op == "run":
                data = _raw_row_to_run(payload["row"])
                project = data.get("project")
                self._projects[data["uuid"]] = project
                ev_type = "run"
            elif op == "delete_run":
                # the payload carries the project (stamped before the
                # row died — a post-delete get_run can only answer None
                # and would hide the deletion from scoped watchers);
                # the cache is the fallback for pre-r14 changelog rows
                project = (payload.get("project")
                           or self._project_of(payload["uuid"]))
                self._projects.pop(payload["uuid"], None)
                data = {"uuid": payload["uuid"], "project": project}
                ev_type = "delete"
            elif op == "alert":
                # alert transitions (ISSUE 20) are fleet-scoped operator
                # surface, not project data: project stays None, so the
                # _visible rule delivers them to UNSCOPED watchers (the
                # operator dashboard) and keeps them from project-scoped
                # tokens — fleet health is not tenant data
                project = None
                data = payload
                ev_type = "alert"
            else:  # heartbeat
                project = self._project_of(payload["uuid"])
                data = payload
                ev_type = "heartbeat"
            token = _fmt_token(epoch, seq)
            out.append({"type": ev_type, "seq": seq, "epoch": epoch,
                        "id": token, "project": project, "data": data,
                        # frame bytes encoded ONCE per event (executor
                        # side): the loop fans the same bytes to every
                        # watcher instead of json.dumps-ing the row
                        # O(watchers) times on the hot path
                        "frame": _sse_frame(ev_type, token, data)})
        return out

    def _project_of(self, uuid: str) -> Optional[str]:
        if uuid not in self._projects:
            run = self.store.get_run(uuid)
            self._projects[uuid] = run.get("project") if run else None
        return self._projects[uuid]

    async def _run(self) -> None:
        assert self._loop is not None and self._wake is not None
        while self._running:
            try:
                await asyncio.wait_for(self._wake.wait(),
                                       timeout=self.poll_interval)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            if not self._running:
                return
            try:
                epoch, cursor, events = await self._loop.run_in_executor(
                    None, self._fetch)
            except CompactedLogError:
                # the hub itself lagged behind a compaction (it was
                # wedged, or the floor raced far ahead): the gap is
                # unreadable — resync everyone, restart from the head
                await self._resync()
                continue
            except Exception:
                # store weather (outage window mid-failover): back off,
                # the FailoverStore/standby sorts itself out underneath
                await asyncio.sleep(min(self.poll_interval, 0.5))
                continue
            if epoch != self._epoch:
                # a failover (or in-proc promotion) moved the epoch: the
                # seq space may have diverged by the replication lag —
                # the only loss-free answer is a full resync (the same
                # verdict a 410 gives a reconnecting client)
                await self._resync()
                continue
            for ev in events:
                if ev["epoch"] != self._epoch:
                    # an epoch boundary INSIDE the batch (in-proc
                    # promotion): deliver nothing past it — resync
                    await self._resync()
                    break
                self._publish(ev)
                self._cursor = ev["seq"]
            else:
                self._cursor = max(self._cursor, cursor)

    async def _resync(self) -> None:
        for w in list(self._watchers.values()):
            self._evict(w, EVICT_RESYNC)
        try:
            assert self._loop is not None
            self._epoch, self._cursor = await self._loop.run_in_executor(
                None, self._read_head)
        except Exception:
            await asyncio.sleep(min(self.poll_interval, 0.5))

    def _publish(self, ev: dict) -> None:
        self._c_events.inc()
        for w in list(self._watchers.values()):
            if not _visible(ev, w.project):
                continue
            try:
                w.queue.put_nowait(ev)
            except asyncio.QueueFull:
                # the bounded-buffer contract: the laggard is evicted;
                # it resumes by Last-Event-ID, everyone else never
                # notices (the hub NEVER awaits a watcher)
                self._evict(w, EVICT_SLOW)

    def _evict(self, w: _Watcher, reason: str, count: bool = True) -> None:
        if w.evicted:
            return
        w.evicted = True
        w.reason = reason
        for wid, cand in list(self._watchers.items()):
            if cand is w:
                del self._watchers[wid]
        if count:
            self._c_evicted[reason].inc()
        # make room for the control sentinel if the queue is full — the
        # dropped event is moot: eviction already means "resume by id"
        try:
            w.queue.put_nowait(_Ctl(reason))
        except asyncio.QueueFull:
            try:
                w.queue.get_nowait()
            except asyncio.QueueEmpty:
                pass
            try:
                w.queue.put_nowait(_Ctl(reason))
            except asyncio.QueueFull:
                pass

    # -- subscription handler ---------------------------------------------

    async def handle(self, request: web.Request) -> web.StreamResponse:
        """GET /api/v1/streams/runs — the SSE subscription endpoint."""
        q = request.rel_url.query
        if "cursor" in q:
            # a keyset-pagination cursor is a DIFFERENT token kind; with
            # a Last-Event-ID (or at all) it is ambiguous which position
            # the caller means — reject instead of guessing
            return web.json_response(
                {"error": "cursor is a pagination token; the stream "
                          "resumes from since / Last-Event-ID only"},
                status=400)
        if not getattr(self.store, "_replicate", True):
            return web.json_response(
                {"error": "change feed disabled on this store "
                          "(replicate=False)"}, status=503,
                headers={"Retry-After": "30"})
        # Last-Event-ID wins over ?since=: a browser EventSource re-sends
        # its original query string on auto-reconnect, and the header is
        # strictly newer than whatever the query asked for at open time
        token = request.headers.get("Last-Event-ID") or q.get("since")
        # a token-scoped subscription is PINNED to its project — the
        # query must never widen it (?project=other on a scoped token
        # would leak other tenants' deltas)
        scope = request.get("scope_project")
        project = scope if scope is not None else q.get("project")
        if not self._running:
            return web.json_response(
                {"error": "stream hub not running"}, status=503,
                headers={"Retry-After": "2"})
        if len(self._watchers) >= self.max_watchers:
            # bounded admission (the PR-12 shedding idiom): an honest
            # 503 + Retry-After beats N+1 watchers all timing out
            self._c_rejected.inc()
            return web.json_response(
                {"error": f"watcher limit reached "
                          f"({self.max_watchers}); retry later"},
                status=503, headers={"Retry-After": "2"})
        assert self._loop is not None
        resume_seq: Optional[int] = None
        if token:
            try:
                # epoch validation: a pre-failover token raises
                # StaleEpochError -> the conflict middleware's 410
                resume_seq = self.store.parse_since(token)
            except StaleEpochError:
                raise
            except (ValueError, TypeError):
                # malformed token (non-numeric seq, '1:2:3'): the
                # caller's input is wrong, not stale — 400, never a 500
                return web.json_response(
                    {"error": f"invalid feed token {token!r} (expected "
                              "a change_seq int, optionally "
                              "epoch-qualified as epoch:seq)"},
                    status=400)

        # register BEFORE any await: the queue starts buffering live
        # events at exactly the hub cursor, so backlog (<= reg_cursor)
        # plus queue (> reg_cursor) is gap-free and duplicate-free
        w = _Watcher(self.buffer, project)
        reg_cursor, reg_epoch = self._cursor, self._epoch
        wid = self._next_id = self._next_id + 1
        self._watchers[wid] = w
        resp: Optional[web.StreamResponse] = None
        try:
            backlog: list[dict] = []
            if resume_seq is not None and resume_seq < reg_cursor:
                try:
                    backlog = await self._loop.run_in_executor(
                        None, self._catch_up, resume_seq, reg_cursor)
                except CompactedLogError as e:
                    self._drop(wid, w)
                    return web.json_response(
                        {"error": "feed token compacted away",
                         "detail": str(e), "floor": e.floor}, status=410)

            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Accel-Buffering": "no",
            })
            await resp.prepare(request)
            if self.write_high_water is not None and \
                    request.transport is not None:
                request.transport.set_write_buffer_limits(
                    high=self.write_high_water)
                sock = request.transport.get_extra_info("socket")
                if sock is not None:
                    import socket as _socket

                    try:
                        sock.setsockopt(_socket.SOL_SOCKET,
                                        _socket.SO_SNDBUF,
                                        self.write_high_water)
                    except OSError:
                        pass
            await self._write(resp, "retry: 3000\n\n".encode())
            # hello carries the subscriber's loss-free anchor: the resume
            # token when it brought one (deltas replay from exactly
            # there), else the current head (list first, then trust
            # deltas after this token)
            last = resume_seq if resume_seq is not None else reg_cursor
            hello = {"since": _fmt_token(reg_epoch, last),
                     "epoch": reg_epoch}
            await self._write(resp, _sse_frame(
                "hello", _fmt_token(reg_epoch, last), hello))
            for ev in backlog:
                if not _visible(ev, project):
                    continue
                await self._write(resp, ev["frame"])
                last = ev["seq"]
            while True:
                try:
                    item = await asyncio.wait_for(
                        w.queue.get(), timeout=self.keepalive_s)
                except asyncio.TimeoutError:
                    # liveness ping; also how a silently-dead peer is
                    # noticed (the write eventually fails/times out)
                    await self._write(resp, b": ping\n\n")
                    continue
                if isinstance(item, _Ctl):
                    frame = _sse_frame(
                        "resync" if item.reason == EVICT_RESYNC
                        else "evicted",
                        None,
                        {"reason": item.reason, "epoch": self._epoch})
                    try:
                        # CancelledError must NOT be swallowed here — a
                        # cancelled handler (client gone, shutdown) has
                        # to unwind, not run on into write_eof
                        await self._write(resp, frame)
                    except (asyncio.TimeoutError, ConnectionError):
                        pass
                    break
                if item["seq"] <= last:
                    continue  # already sent via the backlog walk
                await self._write(resp, item["frame"])
                last = item["seq"]
            try:
                await resp.write_eof()
            except Exception:
                pass
            return resp
        except asyncio.TimeoutError:
            # write timed out: the peer is wedged (kernel buffers full);
            # count it as its own eviction reason, close, move on — a
            # dead-peer stream ending is routine, not a handler error
            if not w.evicted:
                self._c_evicted[EVICT_WRITE_TIMEOUT].inc()
            if resp is None:
                raise
            resp.force_close()
            return resp
        except ConnectionResetError:
            # the peer vanished mid-stream — the normal way an SSE
            # subscription ends; nothing to answer, nothing to log
            if resp is None:
                raise
            return resp
        finally:
            self._drop(wid, w)

    def _drop(self, wid: int, w: _Watcher) -> None:
        if self._watchers.get(wid) is w:
            del self._watchers[wid]

    def _catch_up(self, after_seq: int, upto_seq: int) -> list[dict]:
        """Backlog for a Last-Event-ID resume: changelog rows in
        (after_seq, upto_seq], paged. Runs in the executor. Raises
        CompactedLogError when the resume point predates the floor."""
        rows: list[dict] = []
        cursor = after_seq
        while cursor < upto_seq:
            page = self.store.get_changelog(cursor, 500)
            if not page:
                break
            for rec in page:
                if rec["seq"] > upto_seq:
                    break
                rows.append(rec)
            cursor = page[-1]["seq"]
            if len(page) < 500:
                break
        return self._to_events(rows)

    async def _write(self, resp: web.StreamResponse, data: bytes) -> None:
        await asyncio.wait_for(resp.write(data), timeout=self.write_timeout_s)


def _visible(ev: dict, project: Optional[str]) -> bool:
    """Project scoping: an unfiltered watcher sees everything; a filtered
    one sees only its project — events whose project is UNKNOWN never
    leak to a filtered watcher."""
    if project is None:
        return True
    return ev.get("project") == project


def _sse_frame(ev_type: str, ev_id: Optional[str], data: dict) -> bytes:
    lines = []
    if ev_id is not None:
        lines.append(f"id: {ev_id}")
    lines.append(f"event: {ev_type}")
    lines.append(f"data: {json.dumps(data, separators=(',', ':'))}")
    return ("\n".join(lines) + "\n\n").encode()


def _raw_row_to_run(row: dict) -> dict:
    """A changelog run payload (JSON columns as stored TEXT) -> the
    client-shape run dict the listing endpoints serve, including the
    derived heartbeat_age_s / heartbeat_step_age_s stamps the dashboard
    badges read (same rules as Store.list_runs)."""
    d = dict(row)
    for c in Store._JSON_COLS:
        if c in d:
            d[c] = json.loads(d[c]) if d[c] else None
    if d.get("status") in ("starting", "running"):
        age = age_seconds(d.get("heartbeat_at") or d.get("started_at"))
        if age is not None:
            d["heartbeat_age_s"] = round(age, 3)
        if d.get("heartbeat_step") is not None:
            sage = age_seconds(d.get("heartbeat_step_at"))
            if sage is not None:
                d["heartbeat_step_age_s"] = round(sage, 3)
    return d
