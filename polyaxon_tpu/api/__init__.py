"""REST + streams API service over SQLite (upstream haupt equivalent)."""

from .app import ApiApp, run_artifacts_dir
from .server import ApiServer
from .store import Store
