"""Minimal dashboard (upstream `ui/` — SURVEY.md §2 "UI" row, here a
single static page over the existing REST endpoints: runs table, status,
metrics sparkline, log tail). Served at ``GET /`` by the API app; no build
step, no dependencies — vanilla JS + fetch."""

UI_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8"/>
<title>polyaxon_tpu</title>
<style>
  body { font-family: -apple-system, system-ui, sans-serif; margin: 0;
         background: #f6f7f9; color: #1a1f36; }
  header { background: #1a1f36; color: #fff; padding: 10px 20px;
           display: flex; gap: 16px; align-items: baseline; }
  header h1 { font-size: 16px; margin: 0; }
  header input { margin-left: auto; font-size: 12px; padding: 2px 6px; }
  main { display: flex; gap: 16px; padding: 16px; }
  section { background: #fff; border: 1px solid #e3e8ee; border-radius: 6px;
            padding: 12px; }
  #runs { width: 46%; } #detail { flex: 1; min-width: 0; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 4px 8px; border-bottom: 1px solid #eef1f4; }
  tr:hover td { background: #f0f4ff; cursor: pointer; }
  .st { padding: 1px 7px; border-radius: 9px; font-size: 11px; color: #fff; }
  .st.succeeded { background: #18794e; } .st.failed { background: #cd2b31; }
  .st.running { background: #0b68cb; } .st.stopped { background: #6c757d; }
  .st.created, .st.compiled, .st.queued, .st.scheduled, .st.starting,
  .st.stopping { background: #b98900; }
  pre { background: #0f1320; color: #d6deeb; padding: 10px; border-radius: 6px;
        max-height: 320px; overflow: auto; font-size: 12px; }
  svg { background: #fbfcfe; border: 1px solid #eef1f4; border-radius: 4px; }
  h2 { font-size: 14px; margin: 4px 0 10px; } h3 { font-size: 12px; margin: 12px 0 6px; }
  select { font-size: 13px; }
  .muted { color: #697386; font-size: 12px; }
</style>
</head>
<body>
<header>
  <h1>polyaxon_tpu</h1>
  <select id="project"></select>
  <span class="muted" id="count"></span>
  <input id="token" placeholder="auth token (if required)" type="password"/>
</header>
<main>
  <section id="runs"><h2>Runs</h2><table id="runsTable">
    <thead><tr><th>name</th><th>kind</th><th>status</th><th>uuid</th></tr></thead>
    <tbody></tbody></table></section>
  <section id="detail"><h2 id="dTitle">Select a run</h2>
    <div id="dBody"></div></section>
</main>
<script>
const $ = (s) => document.querySelector(s);
const tokenBox = $("#token");
tokenBox.value = localStorage.getItem("plx_token") || "";
tokenBox.addEventListener("change", () => {
  localStorage.setItem("plx_token", tokenBox.value); refresh();
});
function hdrs() {
  const t = tokenBox.value;
  return t ? {"Authorization": "Bearer " + t} : {};
}
async function j(path) {
  const r = await fetch(path, {headers: hdrs()});
  if (!r.ok) throw new Error(r.status + " " + path);
  return r.json();
}
async function text(path) {
  const r = await fetch(path, {headers: hdrs()});
  return r.ok ? r.text() : "";
}
let project = null, selected = null;
async function loadProjects() {
  const ps = await j("/api/v1/projects");
  const sel = $("#project");
  sel.innerHTML = "";
  for (const p of ps) {
    const o = document.createElement("option");
    o.value = o.textContent = p.name; sel.appendChild(o);
  }
  if (!project && ps.length) project = ps[0].name;
  sel.value = project || "";
  sel.onchange = () => { project = sel.value; refresh(); };
}
function stBadge(s) { return `<span class="st ${s}">${s}</span>`; }
async function loadRuns() {
  if (!project) return;
  const runs = await j(`/api/v1/${project}/runs?limit=100`);
  $("#count").textContent = runs.length + " runs";
  const tb = $("#runsTable tbody");
  tb.innerHTML = "";
  for (const r of runs) {
    const tr = document.createElement("tr");
    tr.innerHTML = `<td>${r.name || ""}</td><td>${r.kind || ""}</td>` +
      `<td>${stBadge(r.status)}</td><td class="muted">${r.uuid.slice(0,8)}</td>`;
    tr.onclick = () => { selected = r.uuid; loadDetail(); };
    tb.appendChild(tr);
  }
}
function sparkline(events) {
  const vals = events.map(e => e.metric).filter(v => typeof v === "number");
  if (!vals.length) return "";
  const w = 420, h = 80, min = Math.min(...vals), max = Math.max(...vals);
  const pts = vals.map((v, i) => {
    const x = (i / Math.max(vals.length - 1, 1)) * (w - 10) + 5;
    const y = h - 5 - ((v - min) / (max - min || 1)) * (h - 10);
    return `${x.toFixed(1)},${y.toFixed(1)}`;
  }).join(" ");
  return `<svg width="${w}" height="${h}"><polyline fill="none" ` +
    `stroke="#0b68cb" stroke-width="1.5" points="${pts}"/></svg>` +
    `<div class="muted">min ${min.toPrecision(4)} · last ` +
    `${vals[vals.length-1].toPrecision(4)}</div>`;
}
async function loadDetail() {
  if (!selected) return;
  const r = await j(`/api/v1/${project}/runs/${selected}`);
  $("#dTitle").innerHTML = `${r.name || r.uuid} ${stBadge(r.status)}`;
  let html = "";
  if (r.outputs) html += `<h3>Outputs</h3><pre>` +
    JSON.stringify(r.outputs, null, 2) + `</pre>`;
  try {
    const m = await j(`/api/v1/${project}/runs/${selected}/metrics`);
    for (const [name, events] of Object.entries(m)) {
      const sl = sparkline(events);
      if (sl) html += `<h3>${name}</h3>` + sl;
    }
  } catch (e) {}
  const logs = await text(`/api/v1/${project}/runs/${selected}/logs`);
  if (logs) html += `<h3>Logs</h3><pre>${logs.replace(/</g, "&lt;")}</pre>`;
  $("#dBody").innerHTML = html || '<span class="muted">no data yet</span>';
}
async function refresh() {
  try { await loadProjects(); await loadRuns(); if (selected) await loadDetail(); }
  catch (e) { $("#count").textContent = String(e); }
}
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
