"""Dashboard (upstream `ui/` — SURVEY.md §2 "UI" row; VERDICT r3 #10
"dashboard v2"): a single static page over the existing REST endpoints.

v2 features: runs table with status filter, real metric line charts (axes,
ticks, grid, hover readout) drawn from the metric event files, multi-run
compare (check runs -> overlaid per-metric charts + params/outputs table),
an artifact browser over ``/artifacts/tree`` with per-file download links
(profile traces highlighted), statuses timeline, and a live log tail.
No build step, no dependencies — vanilla JS + fetch + inline SVG.
"""

UI_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8"/>
<title>polyaxon_tpu</title>
<style>
  body { font-family: -apple-system, system-ui, sans-serif; margin: 0;
         background: #f6f7f9; color: #1a1f36; }
  header { background: #1a1f36; color: #fff; padding: 10px 20px;
           display: flex; gap: 16px; align-items: baseline; }
  header h1 { font-size: 16px; margin: 0; }
  header input { margin-left: auto; font-size: 12px; padding: 2px 6px; }
  main { display: flex; gap: 16px; padding: 16px; align-items: flex-start; }
  section { background: #fff; border: 1px solid #e3e8ee; border-radius: 6px;
            padding: 12px; }
  #runs { width: 40%; } #detail { flex: 1; min-width: 0; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 4px 8px; border-bottom: 1px solid #eef1f4; }
  tr:hover td { background: #f0f4ff; cursor: pointer; }
  .st { padding: 1px 7px; border-radius: 9px; font-size: 11px; color: #fff; }
  .st.succeeded { background: #18794e; } .st.failed { background: #cd2b31; }
  .st.running { background: #0b68cb; } .st.stopped { background: #6c757d; }
  .st.skipped { background: #6c757d; }
  .st.created, .st.compiled, .st.queued, .st.scheduled, .st.starting,
  .st.stopping { background: #b98900; }
  pre { background: #0f1320; color: #d6deeb; padding: 10px; border-radius: 6px;
        max-height: 340px; overflow: auto; font-size: 12px; }
  svg.chart { background: #fbfcfe; border: 1px solid #eef1f4; border-radius: 4px; }
  h2 { font-size: 14px; margin: 4px 0 10px; } h3 { font-size: 12px; margin: 12px 0 6px; }
  select { font-size: 13px; }
  .muted { color: #697386; font-size: 12px; }
  .tabs { display: flex; gap: 2px; margin-bottom: 10px; border-bottom: 1px solid #e3e8ee; }
  .tabs button { border: none; background: none; padding: 6px 12px; font-size: 13px;
                 cursor: pointer; border-bottom: 2px solid transparent; color: #697386; }
  .tabs button.active { color: #1a1f36; border-bottom-color: #0b68cb; font-weight: 600; }
  .crumb a { color: #0b68cb; cursor: pointer; text-decoration: none; }
  .file a { color: #0b68cb; text-decoration: none; }
  .dir { cursor: pointer; color: #1a1f36; font-weight: 600; }
  .trace { background: #fff7e0; }
  .legend { display: inline-block; width: 10px; height: 10px; border-radius: 2px;
            margin-right: 4px; vertical-align: middle; }
  .cmp { font-size: 12px; }
  #cmpBar { margin: 6px 0; }
  button.small { font-size: 12px; padding: 2px 8px; }
</style>
</head>
<body>
<header>
  <h1>polyaxon_tpu</h1>
  <select id="project"></select>
  <select id="stFilter">
    <option value="">all statuses</option>
    <option>running</option><option>succeeded</option><option>failed</option>
    <option>stopped</option><option>created</option><option>queued</option>
  </select>
  <span class="muted" id="count"></span>
  <input id="token" placeholder="auth token (if required)" type="password"/>
</header>
<main>
  <section id="runs"><h2>Runs</h2>
    <div id="cmpBar" class="muted">check ≥2 runs to compare
      <button class="small" id="cmpBtn" style="display:none">compare</button></div>
    <table id="runsTable">
    <thead><tr><th></th><th>name</th><th>kind</th><th>status</th><th>uuid</th></tr></thead>
    <tbody></tbody></table></section>
  <section id="detail"><h2 id="dTitle">Select a run</h2>
    <div class="tabs" id="tabs" style="display:none">
      <button data-tab="overview" class="active">Overview</button>
      <button data-tab="metrics">Metrics</button>
      <button data-tab="artifacts">Artifacts</button>
      <button data-tab="logs">Logs</button>
    </div>
    <div id="dBody"></div></section>
</main>
<script>
const $ = (s) => document.querySelector(s);
const COLORS = ["#0b68cb", "#cd2b31", "#18794e", "#b98900", "#7c3aed",
                "#0e7490", "#be185d", "#4d7c0f"];
const tokenBox = $("#token");
tokenBox.value = localStorage.getItem("plx_token") || "";
tokenBox.addEventListener("change", () => {
  localStorage.setItem("plx_token", tokenBox.value); refresh();
});
function hdrs() {
  const t = tokenBox.value;
  return t ? {"Authorization": "Bearer " + t} : {};
}
async function j(path) {
  const r = await fetch(path, {headers: hdrs()});
  if (!r.ok) throw new Error(r.status + " " + path);
  return r.json();
}
async function text(path) {
  const r = await fetch(path, {headers: hdrs()});
  return r.ok ? r.text() : "";
}
function esc(s) { return String(s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
                  .split('"').join("&quot;"); }
let project = null, selected = null, tab = "overview", compare = null;
let checked = new Set(), runCache = [];
async function loadProjects() {
  const ps = await j("/api/v1/projects");
  const sel = $("#project");
  sel.innerHTML = "";
  for (const p of ps) {
    const o = document.createElement("option");
    o.value = o.textContent = p.name; sel.appendChild(o);
  }
  if (!project && ps.length) project = ps[0].name;
  sel.value = project || "";
  sel.onchange = () => { project = sel.value; selected = null; compare = null;
                         checked.clear(); refresh(); };
}
function stBadge(s) { return `<span class="st ${s}">${s}</span>`; }
async function loadRuns() {
  if (!project) return;
  const f = $("#stFilter").value;
  const runs = await j(`/api/v1/${project}/runs?limit=200` +
                       (f ? `&status=${f}` : ""));
  runCache = runs;
  $("#count").textContent = runs.length + " runs";
  const tb = $("#runsTable tbody");
  tb.innerHTML = "";
  for (const r of runs) {
    const tr = document.createElement("tr");
    tr.innerHTML =
      `<td><input type="checkbox" data-u="${r.uuid}"` +
      `${checked.has(r.uuid) ? " checked" : ""}/></td>` +
      `<td>${esc(r.name || "")}</td><td>${esc(r.kind || "")}</td>` +
      `<td>${stBadge(r.status)}</td><td class="muted">${r.uuid.slice(0,8)}</td>`;
    tr.querySelector("input").onclick = (ev) => {
      ev.stopPropagation();
      if (ev.target.checked) checked.add(r.uuid); else checked.delete(r.uuid);
      updateCmpBar();
    };
    tr.onclick = () => { selected = r.uuid; compare = null; artPath = ""; render(); };
    tb.appendChild(tr);
  }
  updateCmpBar();
}
function updateCmpBar() {
  $("#cmpBtn").style.display = checked.size >= 2 ? "" : "none";
}
$("#cmpBtn").onclick = () => { compare = [...checked]; selected = null; render(); };
$("#stFilter").onchange = () => loadRuns();

// ---- charts ---------------------------------------------------------------
function niceTicks(lo, hi, n) {
  if (!(hi > lo)) { hi = lo + 1; }
  const span = hi - lo, step0 = span / Math.max(n, 1);
  const mag = Math.pow(10, Math.floor(Math.log10(step0)));
  const step = [1, 2, 5, 10].map(m => m * mag).find(s => span / s <= n + 1) || mag * 10;
  const t = [];
  for (let v = Math.ceil(lo / step) * step; v <= hi + 1e-12; v += step) t.push(v);
  return t;
}
function fmt(v) {
  if (v === 0) return "0";
  const a = Math.abs(v);
  if (a >= 1e5 || a < 1e-3) return v.toExponential(1);
  return String(+v.toPrecision(4));
}
function lineChart(series, opts) {
  // series: [{label, color, pts: [[x, y], ...]}]; real axes + grid + hover
  const w = opts.w || 520, h = opts.h || 200, mL = 52, mR = 10, mT = 8, mB = 22;
  const all = series.flatMap(s => s.pts);
  if (!all.length) return "";
  let xmin = Math.min(...all.map(p => p[0])), xmax = Math.max(...all.map(p => p[0]));
  let ymin = Math.min(...all.map(p => p[1])), ymax = Math.max(...all.map(p => p[1]));
  if (xmax === xmin) xmax = xmin + 1;
  if (ymax === ymin) { ymax += Math.abs(ymax) * 0.05 + 1e-9; ymin -= Math.abs(ymin) * 0.05 + 1e-9; }
  const X = x => mL + (x - xmin) / (xmax - xmin) * (w - mL - mR);
  const Y = y => h - mB - (y - ymin) / (ymax - ymin) * (h - mT - mB);
  let g = "";
  for (const ty of niceTicks(ymin, ymax, 5)) {
    const y = Y(ty);
    g += `<line x1="${mL}" y1="${y}" x2="${w - mR}" y2="${y}" stroke="#eef1f4"/>` +
         `<text x="${mL - 6}" y="${y + 3}" font-size="10" fill="#697386" ` +
         `text-anchor="end">${fmt(ty)}</text>`;
  }
  for (const tx of niceTicks(xmin, xmax, 6)) {
    const x = X(tx);
    g += `<line x1="${x}" y1="${mT}" x2="${x}" y2="${h - mB}" stroke="#f4f6f8"/>` +
         `<text x="${x}" y="${h - 8}" font-size="10" fill="#697386" ` +
         `text-anchor="middle">${fmt(tx)}</text>`;
  }
  let lines = "";
  for (const s of series) {
    const pts = s.pts.map(p => `${X(p[0]).toFixed(1)},${Y(p[1]).toFixed(1)}`).join(" ");
    lines += `<polyline fill="none" stroke="${s.color}" stroke-width="1.5" points="${pts}"/>`;
  }
  const id = "c" + Math.random().toString(36).slice(2, 8);
  const chart =
    `<svg class="chart" id="${id}" width="${w}" height="${h}">` + g + lines +
    `<line id="${id}x" x1="0" y1="${mT}" x2="0" y2="${h - mB}" stroke="#b98900" ` +
    `stroke-dasharray="3,2" visibility="hidden"/>` +
    `<text id="${id}t" x="${mL + 4}" y="${mT + 10}" font-size="10" fill="#1a1f36"></text>` +
    `</svg>`;
  // post-render hover wiring
  setTimeout(() => {
    const el = document.getElementById(id);
    if (!el) return;
    el.addEventListener("mousemove", ev => {
      const r = el.getBoundingClientRect();
      const px = ev.clientX - r.left;
      if (px < mL || px > w - mR) return;
      const xv = xmin + (px - mL) / (w - mL - mR) * (xmax - xmin);
      const parts = series.map(s => {
        if (!s.pts.length) return null;
        let best = s.pts[0];
        for (const p of s.pts) if (Math.abs(p[0] - xv) < Math.abs(best[0] - xv)) best = p;
        return `${s.label}: ${fmt(best[1])}`;
      }).filter(Boolean);
      document.getElementById(id + "x").setAttribute("x1", px);
      document.getElementById(id + "x").setAttribute("x2", px);
      document.getElementById(id + "x").setAttribute("visibility", "visible");
      document.getElementById(id + "t").textContent =
        `x=${fmt(xv)}  ` + parts.join("  ");
    });
    el.addEventListener("mouseleave", () => {
      document.getElementById(id + "x").setAttribute("visibility", "hidden");
      document.getElementById(id + "t").textContent = "";
    });
  }, 0);
  return chart;
}
function toPts(events) {
  const pts = [];
  events.forEach((e, i) => {
    if (typeof e.metric === "number")
      pts.push([typeof e.step === "number" ? e.step : i, e.metric]);
  });
  return pts;
}
function legendHtml(series) {
  return series.map(s =>
    `<span class="legend" style="background:${s.color}"></span>` +
    `<span class="muted">${esc(s.label)}</span>`).join(" &nbsp; ");
}

// ---- detail tabs ----------------------------------------------------------
document.querySelectorAll("#tabs button").forEach(b => {
  b.onclick = () => { tab = b.dataset.tab; render(); };
});
async function renderOverview(r) {
  let html = `<table class="cmp"><tr><th></th><th>value</th></tr>`;
  for (const k of ["uuid", "kind", "created_at", "started_at", "finished_at"])
    if (r[k]) html += `<tr><td class="muted">${k}</td><td>${esc(r[k])}</td></tr>`;
  html += `</table>`;
  if (r.inputs && Object.keys(r.inputs).length)
    html += `<h3>Params</h3><pre>${esc(JSON.stringify(r.inputs, null, 2))}</pre>`;
  if (r.outputs)
    html += `<h3>Outputs</h3><pre>${esc(JSON.stringify(r.outputs, null, 2))}</pre>`;
  try {
    const sts = await j(`/api/v1/${project}/runs/${r.uuid}/statuses`);
    html += `<h3>Status timeline</h3><table class="cmp">`;
    for (const s of sts) html +=
      `<tr><td>${stBadge(s.type || s.status || "")}</td>` +
      `<td class="muted">${esc(s.created_at || "")}</td>` +
      `<td class="muted">${esc(s.reason || "")}</td></tr>`;
    html += `</table>`;
  } catch (e) {}
  return html;
}
async function renderMetrics(r) {
  let html = "";
  try {
    const m = await j(`/api/v1/${project}/runs/${r.uuid}/metrics`);
    const names = Object.keys(m).sort();
    if (!names.length) return '<span class="muted">no metrics yet</span>';
    for (const name of names) {
      const pts = toPts(m[name]);
      if (!pts.length) continue;
      const series = [{label: name, color: COLORS[0], pts}];
      const last = pts[pts.length - 1][1];
      html += `<h3>${esc(name)} <span class="muted">last ${fmt(last)}</span></h3>` +
              lineChart(series, {});
    }
  } catch (e) { html = `<span class="muted">${esc(e)}</span>`; }
  return html;
}
let artPath = "";
function isTrace(name) {
  return /\\.trace\\.json(\\.gz)?$|\\.pb$|perfetto|xplane/.test(name);
}
async function renderArtifacts(r) {
  let html = "";
  try {
    const t = await j(`/api/v1/${project}/runs/${r.uuid}/artifacts/tree` +
                      (artPath ? `?path=${encodeURIComponent(artPath)}` : ""));
    const crumbs = ["<a data-p=''>artifacts</a>"];
    let acc = "";
    for (const part of (artPath ? artPath.split("/") : [])) {
      acc = acc ? acc + "/" + part : part;
      crumbs.push(`<a data-p="${esc(acc)}">${esc(part)}</a>`);
    }
    html += `<div class="crumb">${crumbs.join(" / ")}</div><table class="cmp">`;
    for (const d of t.dirs)
      html += `<tr class="dirrow"><td class="dir" data-p="` +
        esc(artPath ? artPath + "/" + d : d) + `">📁 ${esc(d)}</td><td></td></tr>`;
    for (const f of t.files) {
      const rel = artPath ? artPath + "/" + f.name : f.name;
      const href = `/api/v1/${project}/runs/${r.uuid}/artifacts/file?path=` +
                   encodeURIComponent(rel);
      html += `<tr${isTrace(f.name) ? ' class="trace"' : ""}><td class="file">` +
        `<a href="${href}" download>${esc(f.name)}</a>` +
        `${isTrace(f.name) ? ' <span class="muted">(profile trace)</span>' : ""}</td>` +
        `<td class="muted">${(f.size / 1024).toFixed(1)} KB</td></tr>`;
    }
    html += `</table>`;
  } catch (e) { html = `<span class="muted">no artifacts</span>`; }
  return html;
}
async function renderLogs(r) {
  const logs = await text(`/api/v1/${project}/runs/${r.uuid}/logs?tail=400`);
  return logs ? `<pre>${esc(logs)}</pre>` : '<span class="muted">no logs yet</span>';
}
async function renderCompare(uuids) {
  const runs = await Promise.all(
    uuids.map(u => j(`/api/v1/${project}/runs/${u}`)));
  $("#dTitle").textContent = `Compare ${runs.length} runs`;
  $("#tabs").style.display = "none";
  const label = r => r.name || r.uuid.slice(0, 8);
  let html = `<h3>Runs</h3><table class="cmp"><tr><th></th><th>run</th>` +
             `<th>status</th><th>params</th><th>outputs</th></tr>`;
  runs.forEach((r, i) => {
    html += `<tr><td><span class="legend" style="background:${COLORS[i % COLORS.length]}">` +
      `</span></td><td>${esc(label(r))}</td><td>${stBadge(r.status)}</td>` +
      `<td><pre style="max-height:80px">${esc(JSON.stringify(r.inputs || {}))}</pre></td>` +
      `<td><pre style="max-height:80px">${esc(JSON.stringify(r.outputs || {}))}</pre></td></tr>`;
  });
  html += `</table>`;
  const all = await Promise.all(
    uuids.map(u => j(`/api/v1/${project}/runs/${u}/metrics`).catch(() => ({}))));
  const names = [...new Set(all.flatMap(m => Object.keys(m)))].sort();
  for (const name of names) {
    const series = [];
    runs.forEach((r, i) => {
      const pts = toPts(all[i][name] || []);
      if (pts.length) series.push(
        {label: label(r), color: COLORS[i % COLORS.length], pts});
    });
    if (!series.length) continue;
    html += `<h3>${esc(name)}</h3><div>${legendHtml(series)}</div>` +
            lineChart(series, {});
  }
  $("#dBody").innerHTML = html;
}
async function render() {
  if (compare && compare.length >= 2) return renderCompare(compare);
  if (!selected) return;
  const r = await j(`/api/v1/${project}/runs/${selected}`);
  $("#dTitle").innerHTML = `${esc(r.name || r.uuid)} ${stBadge(r.status)}`;
  $("#tabs").style.display = "";
  document.querySelectorAll("#tabs button").forEach(b =>
    b.classList.toggle("active", b.dataset.tab === tab));
  let html = "";
  if (tab === "overview") html = await renderOverview(r);
  else if (tab === "metrics") html = await renderMetrics(r);
  else if (tab === "artifacts") html = await renderArtifacts(r);
  else if (tab === "logs") html = await renderLogs(r);
  $("#dBody").innerHTML = html || '<span class="muted">no data yet</span>';
  if (tab === "artifacts") {
    document.querySelectorAll("#dBody .dir, #dBody .crumb a").forEach(el => {
      el.onclick = () => { artPath = el.dataset.p || ""; render(); };
    });
  }
}
async function refresh() {
  try { await loadProjects(); await loadRuns();
        if (selected || compare) await render(); }
  catch (e) { $("#count").textContent = String(e); }
}
refresh();
setInterval(refresh, 4000);
</script>
</body>
</html>
"""
