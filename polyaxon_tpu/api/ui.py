"""Dashboard (upstream `ui/` — SURVEY.md §2 "UI" row; VERDICT r3 #10
"dashboard v2", r4 #4 "sweep UI"): a single static page over the existing
REST endpoints.

v2 features: runs table with status filter, real metric line charts (axes,
ticks, grid, hover readout) drawn from the metric event files, multi-run
compare (check runs -> overlaid per-metric charts + params/outputs table),
an artifact browser over ``/artifacts/tree`` with per-file download links
(profile traces highlighted), statuses timeline, and a live log tail.

v3 (round 5) adds the tuning views: the runs table groups pipeline
children under their parent as a collapsible tree with live statuses, and
pipeline runs get a **Sweep** tab — params-vs-metric scatter and a
parallel-coordinates plot over the children's recorded inputs/outputs
(queryable since the r4 store work), plus a ranked leaderboard. Open a
finished ASHA sweep and see which params won without the CLI.

v4 (round 7): the runs table pages through the cursor-paginated envelope
listing (100 per page, prev/next + total count) instead of rendering one
giant fetch — thousands-of-runs projects stay responsive and each refresh
costs the server O(page) (VERDICT r5 weak #7, docs/PERFORMANCE.md
"Control-plane performance").

v5 (observability, ISSUE 5): a **Timeline** tab renders the run's merged
trace (control-plane lifecycle spans + pod-side training spans from
``/timeline``) as a waterfall; the runs table badges zombie-suspect runs
(⚠ when ``heartbeat_age_s`` > 60); the Metrics tab renders ``curve``
events as line charts and ``confusion`` events as heat-shaded matrices.
No build step, no dependencies — vanilla JS + fetch + inline SVG.

v6 (live push, ISSUE 14): the 4s ``setInterval`` full re-render is DEAD.
The page subscribes to the SSE change feed (``/api/v1/streams/runs``)
and applies run deltas in place — run-table updates, the log tail,
timeline and metrics refresh ride ``run``/``heartbeat`` events, so a
steady-state session issues ZERO periodic re-list calls after the
initial load (tested: tests/test_stream.py dashboard contract). Interval
polling survives strictly as the fallback: when ``EventSource`` is
missing or the stream fails 3+ times, the old ``refresh()`` loop takes
over while the stream is re-probed in the background. A ``resync``
control event (store failover / epoch rollover) triggers one full
re-list plus a fresh subscription — never a silently-diverged table.
"""

UI_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8"/>
<title>polyaxon_tpu</title>
<style>
  body { font-family: -apple-system, system-ui, sans-serif; margin: 0;
         background: #f6f7f9; color: #1a1f36; }
  header { background: #1a1f36; color: #fff; padding: 10px 20px;
           display: flex; gap: 16px; align-items: baseline; }
  header h1 { font-size: 16px; margin: 0; }
  header input { margin-left: auto; font-size: 12px; padding: 2px 6px; }
  main { display: flex; gap: 16px; padding: 16px; align-items: flex-start; }
  section { background: #fff; border: 1px solid #e3e8ee; border-radius: 6px;
            padding: 12px; }
  #runs { width: 40%; } #detail { flex: 1; min-width: 0; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 4px 8px; border-bottom: 1px solid #eef1f4; }
  tr:hover td { background: #f0f4ff; cursor: pointer; }
  .st { padding: 1px 7px; border-radius: 9px; font-size: 11px; color: #fff; }
  .st.succeeded { background: #18794e; } .st.failed { background: #cd2b31; }
  .st.running { background: #0b68cb; } .st.stopped { background: #6c757d; }
  .st.skipped { background: #6c757d; }
  .st.created, .st.compiled, .st.queued, .st.scheduled, .st.starting,
  .st.stopping { background: #b98900; }
  pre { background: #0f1320; color: #d6deeb; padding: 10px; border-radius: 6px;
        max-height: 340px; overflow: auto; font-size: 12px; }
  svg.chart { background: #fbfcfe; border: 1px solid #eef1f4; border-radius: 4px; }
  h2 { font-size: 14px; margin: 4px 0 10px; } h3 { font-size: 12px; margin: 12px 0 6px; }
  select { font-size: 13px; }
  .muted { color: #697386; font-size: 12px; }
  .tabs { display: flex; gap: 2px; margin-bottom: 10px; border-bottom: 1px solid #e3e8ee; }
  .tabs button { border: none; background: none; padding: 6px 12px; font-size: 13px;
                 cursor: pointer; border-bottom: 2px solid transparent; color: #697386; }
  .tabs button.active { color: #1a1f36; border-bottom-color: #0b68cb; font-weight: 600; }
  .crumb a { color: #0b68cb; cursor: pointer; text-decoration: none; }
  .file a { color: #0b68cb; text-decoration: none; }
  .dir { cursor: pointer; color: #1a1f36; font-weight: 600; }
  .trace { background: #fff7e0; }
  .legend { display: inline-block; width: 10px; height: 10px; border-radius: 2px;
            margin-right: 4px; vertical-align: middle; }
  .cmp { font-size: 12px; }
  #cmpBar { margin: 6px 0; }
  button.small { font-size: 12px; padding: 2px 8px; }
  .twist { cursor: pointer; color: #697386; user-select: none; }
  .winner td { background: #f0faf4; }
  .prio { font-size: 11px; padding: 1px 6px; border-radius: 8px;
          background: #eef1f6; color: #3c4257; }
  .prio.high { background: #fde8e8; color: #cd2b31; }
  .prio.preemptible { background: #e7f4ec; color: #18794e; }
  .quota { display: inline-block; margin-right: 14px; }
  .quota .qname { font-weight: 600; }
  .qbar { display: inline-block; width: 60px; height: 7px;
          background: #e3e8ee; border-radius: 4px; margin-left: 5px;
          overflow: hidden; vertical-align: middle; }
  .qbar span { display: block; height: 100%; background: #0b68cb; }
</style>
</head>
<body>
<header>
  <h1>polyaxon_tpu</h1>
  <select id="project"></select>
  <select id="stFilter">
    <option value="">all statuses</option>
    <option>running</option><option>succeeded</option><option>failed</option>
    <option>stopped</option><option>created</option><option>queued</option>
  </select>
  <span class="muted" id="count"></span>
  <input id="token" placeholder="auth token (if required)" type="password"/>
</header>
<main>
  <section id="runs"><h2>Runs</h2>
    <div id="alerts" class="muted" style="margin-bottom:6px"></div>
    <div id="clusters" class="muted" style="margin-bottom:6px"></div>
    <div id="quotas" class="muted" style="margin-bottom:6px"></div>
    <div id="cmpBar" class="muted">check ≥2 runs to compare
      <button class="small" id="cmpBtn" style="display:none">compare</button></div>
    <table id="runsTable">
    <thead><tr><th></th><th>name</th><th>kind</th><th>status</th><th>priority</th><th>tenant</th><th>progress</th><th>by</th><th>uuid</th></tr></thead>
    <tbody></tbody></table>
    <div id="pageBar" class="muted" style="margin-top:6px">
      <button class="small" id="prevPg" disabled>&laquo; prev</button>
      <span id="pageInfo"></span>
      <button class="small" id="nextPg" disabled>next &raquo;</button>
    </div></section>
  <section id="detail"><h2 id="dTitle">Select a run</h2>
    <div class="tabs" id="tabs" style="display:none">
      <button data-tab="overview" class="active">Overview</button>
      <button data-tab="metrics">Metrics</button>
      <button data-tab="timeline">Timeline</button>
      <button data-tab="sweep" id="sweepTab" style="display:none">Sweep</button>
      <button data-tab="graph" id="graphTab" style="display:none">Graph</button>
      <button data-tab="artifacts">Artifacts</button>
      <button data-tab="logs">Logs</button>
    </div>
    <div id="dBody"></div></section>
</main>
<script>
const $ = (s) => document.querySelector(s);
const COLORS = ["#0b68cb", "#cd2b31", "#18794e", "#b98900", "#7c3aed",
                "#0e7490", "#be185d", "#4d7c0f"];
const tokenBox = $("#token");
tokenBox.value = localStorage.getItem("plx_token") || "";
tokenBox.addEventListener("change", () => {
  localStorage.setItem("plx_token", tokenBox.value);
  connectStream();  // carries the new token; its hello re-lists
});
function hdrs() {
  const t = tokenBox.value;
  return t ? {"Authorization": "Bearer " + t} : {};
}
async function j(path) {
  const r = await fetch(path, {headers: hdrs()});
  if (!r.ok) throw new Error(r.status + " " + path);
  return r.json();
}
async function text(path) {
  const r = await fetch(path, {headers: hdrs()});
  return r.ok ? r.text() : "";
}
function esc(s) { return String(s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
                  .split('"').join("&quot;"); }
let project = null, selected = null, tab = "overview", compare = null;
let checked = new Set(), runCache = [];
async function loadProjects() {
  const ps = await j("/api/v1/projects");
  const sel = $("#project");
  sel.innerHTML = "";
  for (const p of ps) {
    const o = document.createElement("option");
    o.value = o.textContent = p.name; sel.appendChild(o);
  }
  if (!project && ps.length) project = ps[0].name;
  sel.value = project || "";
  sel.onchange = () => { project = sel.value; selected = null; compare = null;
                         checked.clear(); resetPages(); refresh(); };
}
function stBadge(s) { return `<span class="st ${s}">${s}</span>`; }
let collapsed = new Set();
function addRunRow(tb, r, depth, kids) {
  const tr = document.createElement("tr");
  const pad = depth ? `style="padding-left:${8 + depth * 18}px"` : "";
  const twist = kids.length
    ? `<span class="twist" data-u="${r.uuid}">${collapsed.has(r.uuid) ? "&#9656;" : "&#9662;"}</span> `
    : (depth ? `<span class="muted">&#9492;</span> ` : "");
  const kidNote = kids.length
    ? ` <span class="muted">(${kids.length} children)</span>` : "";
  // zombie-suspect badge: the store stamps heartbeat_age_s onto in-flight
  // listing rows; a run past 60s without a beat is flagged before the
  // reaper acts on it
  const stale = typeof r.heartbeat_age_s === "number" && r.heartbeat_age_s > 60
    ? ` <span title="no heartbeat for ${Math.round(r.heartbeat_age_s)}s` +
      ` — zombie suspect" style="cursor:help">&#9888;</span>` : "";
  // progress column (ISSUE 8): the training step the pod last reported
  // via its heartbeat, with a stalled badge when the step has been
  // FROZEN for 2min while heartbeats stayed fresh — the wedged-step
  // signature the stall-aware reaper acts on
  const stalled = typeof r.heartbeat_step_age_s === "number"
    && r.heartbeat_step_age_s > 120
    && !(typeof r.heartbeat_age_s === "number" && r.heartbeat_age_s > 60)
    ? ` <span title="step frozen for ${Math.round(r.heartbeat_step_age_s)}s` +
      ` with fresh heartbeats — stalled suspect" style="cursor:help">` +
      `&#8987;</span>` : "";
  const progress = typeof r.heartbeat_step === "number"
    ? `step ${r.heartbeat_step}${stalled}` : "";
  // tenancy columns (ISSUE 15): priority-class badge and tenant, plus an
  // over-quota park flag from the meta the agent stamps loudly
  const prio = (r.compiled && r.compiled.priority)
    || (r.spec && r.spec.priority) || "normal";
  const prioCell = prio === "normal"
    ? `<span class="muted">normal</span>`
    : `<span class="prio ${esc(prio)}">${esc(prio)}</span>`;
  const overQ = (r.meta && r.meta.over_quota)
    ? ` <span title="parked: tenant over its chip quota"` +
      ` style="cursor:help">&#9203;</span>` : "";
  // federation (ISSUE 16): which cluster hosts the run, with its hop
  // history (spillovers/failovers) in the hover
  const placed = (r.meta && r.meta.cluster)
    ? ` <span class="muted" title="placed on ${esc(r.meta.cluster)}` +
      `${(r.meta.placement_history || []).length
         ? " via " + r.meta.placement_history.map(esc).join(" → ") : ""}"` +
      ` style="cursor:help">@${esc(r.meta.cluster)}</span>` : "";
  tr.innerHTML =
    `<td><input type="checkbox" data-u="${r.uuid}"` +
    `${checked.has(r.uuid) ? " checked" : ""}/></td>` +
    `<td ${pad}>${twist}${esc(r.name || "")}${kidNote}</td>` +
    `<td>${esc(r.kind || "")}</td>` +
    `<td>${stBadge(r.status)}${stale}${overQ}${placed}</td>` +
    `<td>${prioCell}</td>` +
    `<td class="muted">${esc(r.tenant || "")}</td>` +
    `<td class="muted">${progress}</td>` +
    `<td class="muted">${esc(r.created_by || "")}</td>` +
    `<td class="muted">${r.uuid.slice(0,8)}</td>`;
  tr.querySelector("input").onclick = (ev) => {
    ev.stopPropagation();
    if (ev.target.checked) checked.add(r.uuid); else checked.delete(r.uuid);
    updateCmpBar();
  };
  const tw = tr.querySelector(".twist");
  if (tw) tw.onclick = (ev) => {
    ev.stopPropagation();
    if (collapsed.has(r.uuid)) collapsed.delete(r.uuid);
    else collapsed.add(r.uuid);
    renderRunsTable();
  };
  tr.onclick = () => { selected = r.uuid; compare = null; artPath = ""; render(); };
  tb.appendChild(tr);
  if (!collapsed.has(r.uuid))
    for (const c of kids) addRunRow(tb, c, depth + 1, childrenOf(c.uuid));
}
function childrenOf(uuid) {
  return runCache.filter(r => r.pipeline_uuid === uuid);
}
function renderRunsTable() {
  const tb = $("#runsTable tbody");
  tb.innerHTML = "";
  const present = new Set(runCache.map(r => r.uuid));
  for (const r of runCache) {
    // top level: no parent, or parent not in the listing (filtered out)
    if (r.pipeline_uuid && present.has(r.pipeline_uuid)) continue;
    addRunRow(tb, r, 0, childrenOf(r.uuid));
  }
  updateCmpBar();
}
// keyset pagination over the envelope listing (VERDICT r5 weak #7): the
// table fetches one page, never the project's whole history; cursors for
// visited pages stack up so "prev" replays them without offset scans
const PAGE = 100;
let page = 0, pageCursors = [null], runTotal = 0;
function resetPages() { page = 0; pageCursors = [null]; }
async function loadRuns() {
  if (!project) return;
  const f = $("#stFilter").value;
  const cur = pageCursors[page];
  listInFlight = true;
  let resp;
  try {
    resp = await j(`/api/v1/${project}/runs?paged=1&limit=${PAGE}` +
                   (f ? `&status=${f}` : "") +
                   (cur ? `&cursor=${encodeURIComponent(cur)}` : ""));
  } catch (e) {
    // a failed snapshot must not strand buffered deltas: apply them to
    // the cache we still have (they are newer than it)
    listInFlight = false;
    replayDeltas();
    throw e;
  }
  listInFlight = false;
  runCache = resp.results;
  if (resp.count != null) runTotal = resp.count;  // only page 1 carries it
  // deltas that raced the snapshot re-apply ON TOP of it (the snapshot
  // may predate them; a delta already reflected in it just re-updates
  // its row, so the total never double-counts)
  replayDeltas();
  pageCursors[page + 1] = resp.next_cursor;
  const lo = page * PAGE + (runCache.length ? 1 : 0);
  const hi = page * PAGE + runCache.length;
  $("#count").textContent = `${runTotal} runs`;
  $("#pageInfo").textContent =
    runTotal > PAGE ? `${lo}–${hi} of ${runTotal}` : "";
  $("#pageBar").style.display = runTotal > PAGE ? "" : "none";
  $("#prevPg").disabled = page === 0;
  $("#nextPg").disabled = !resp.next_cursor;
  renderRunsTable();
}
$("#prevPg").onclick = () => { if (page > 0) { page--; loadRuns(); } };
$("#nextPg").onclick = () => {
  if (pageCursors[page + 1]) { page++; loadRuns(); }
};
function updateCmpBar() {
  $("#cmpBtn").style.display = checked.size >= 2 ? "" : "none";
}
$("#cmpBtn").onclick = () => { compare = [...checked]; selected = null; render(); };
$("#stFilter").onchange = () => { resetPages(); loadRuns(); };

// ---- charts ---------------------------------------------------------------
function niceTicks(lo, hi, n) {
  if (!(hi > lo)) { hi = lo + 1; }
  const span = hi - lo, step0 = span / Math.max(n, 1);
  const mag = Math.pow(10, Math.floor(Math.log10(step0)));
  const step = [1, 2, 5, 10].map(m => m * mag).find(s => span / s <= n + 1) || mag * 10;
  const t = [];
  for (let v = Math.ceil(lo / step) * step; v <= hi + 1e-12; v += step) t.push(v);
  return t;
}
function fmt(v) {
  if (v === 0) return "0";
  const a = Math.abs(v);
  if (a >= 1e5 || a < 1e-3) return v.toExponential(1);
  return String(+v.toPrecision(4));
}
function lineChart(series, opts) {
  // series: [{label, color, pts: [[x, y], ...]}]; real axes + grid + hover
  const w = opts.w || 520, h = opts.h || 200, mL = 52, mR = 10, mT = 8, mB = 22;
  const all = series.flatMap(s => s.pts);
  if (!all.length) return "";
  let xmin = Math.min(...all.map(p => p[0])), xmax = Math.max(...all.map(p => p[0]));
  let ymin = Math.min(...all.map(p => p[1])), ymax = Math.max(...all.map(p => p[1]));
  if (xmax === xmin) xmax = xmin + 1;
  if (ymax === ymin) { ymax += Math.abs(ymax) * 0.05 + 1e-9; ymin -= Math.abs(ymin) * 0.05 + 1e-9; }
  const X = x => mL + (x - xmin) / (xmax - xmin) * (w - mL - mR);
  const Y = y => h - mB - (y - ymin) / (ymax - ymin) * (h - mT - mB);
  let g = "";
  for (const ty of niceTicks(ymin, ymax, 5)) {
    const y = Y(ty);
    g += `<line x1="${mL}" y1="${y}" x2="${w - mR}" y2="${y}" stroke="#eef1f4"/>` +
         `<text x="${mL - 6}" y="${y + 3}" font-size="10" fill="#697386" ` +
         `text-anchor="end">${fmt(ty)}</text>`;
  }
  for (const tx of niceTicks(xmin, xmax, 6)) {
    const x = X(tx);
    g += `<line x1="${x}" y1="${mT}" x2="${x}" y2="${h - mB}" stroke="#f4f6f8"/>` +
         `<text x="${x}" y="${h - 8}" font-size="10" fill="#697386" ` +
         `text-anchor="middle">${fmt(tx)}</text>`;
  }
  let lines = "";
  for (const s of series) {
    const pts = s.pts.map(p => `${X(p[0]).toFixed(1)},${Y(p[1]).toFixed(1)}`).join(" ");
    lines += `<polyline fill="none" stroke="${s.color}" stroke-width="1.5" points="${pts}"/>`;
  }
  const id = "c" + Math.random().toString(36).slice(2, 8);
  const chart =
    `<svg class="chart" id="${id}" width="${w}" height="${h}">` + g + lines +
    `<line id="${id}x" x1="0" y1="${mT}" x2="0" y2="${h - mB}" stroke="#b98900" ` +
    `stroke-dasharray="3,2" visibility="hidden"/>` +
    `<text id="${id}t" x="${mL + 4}" y="${mT + 10}" font-size="10" fill="#1a1f36"></text>` +
    `</svg>`;
  // post-render hover wiring
  setTimeout(() => {
    const el = document.getElementById(id);
    if (!el) return;
    el.addEventListener("mousemove", ev => {
      const r = el.getBoundingClientRect();
      const px = ev.clientX - r.left;
      if (px < mL || px > w - mR) return;
      const xv = xmin + (px - mL) / (w - mL - mR) * (xmax - xmin);
      const parts = series.map(s => {
        if (!s.pts.length) return null;
        let best = s.pts[0];
        for (const p of s.pts) if (Math.abs(p[0] - xv) < Math.abs(best[0] - xv)) best = p;
        return `${s.label}: ${fmt(best[1])}`;
      }).filter(Boolean);
      document.getElementById(id + "x").setAttribute("x1", px);
      document.getElementById(id + "x").setAttribute("x2", px);
      document.getElementById(id + "x").setAttribute("visibility", "visible");
      document.getElementById(id + "t").textContent =
        `x=${fmt(xv)}  ` + parts.join("  ");
    });
    el.addEventListener("mouseleave", () => {
      document.getElementById(id + "x").setAttribute("visibility", "hidden");
      document.getElementById(id + "t").textContent = "";
    });
  }, 0);
  return chart;
}
// ---- sweep charts ---------------------------------------------------------
function heat(t) {
  // 0 (best, green) -> 1 (worst, red) through amber
  const h = 140 - 140 * Math.min(Math.max(t, 0), 1);
  return `hsl(${h}, 70%, 45%)`;
}
function scatterChart(pts, xlabel, ylabel) {
  // pts: [{x, y, label, color}]
  const w = 420, h = 230, mL = 56, mR = 12, mT = 10, mB = 30;
  if (!pts.length) return "";
  let xmin = Math.min(...pts.map(p => p.x)), xmax = Math.max(...pts.map(p => p.x));
  let ymin = Math.min(...pts.map(p => p.y)), ymax = Math.max(...pts.map(p => p.y));
  if (xmax === xmin) { xmax += Math.abs(xmax) * 0.05 + 1e-9; xmin -= Math.abs(xmin) * 0.05 + 1e-9; }
  if (ymax === ymin) { ymax += Math.abs(ymax) * 0.05 + 1e-9; ymin -= Math.abs(ymin) * 0.05 + 1e-9; }
  const X = x => mL + (x - xmin) / (xmax - xmin) * (w - mL - mR);
  const Y = y => h - mB - (y - ymin) / (ymax - ymin) * (h - mT - mB);
  let g = "";
  for (const ty of niceTicks(ymin, ymax, 5)) g +=
    `<line x1="${mL}" y1="${Y(ty)}" x2="${w - mR}" y2="${Y(ty)}" stroke="#eef1f4"/>` +
    `<text x="${mL - 6}" y="${Y(ty) + 3}" font-size="10" fill="#697386" text-anchor="end">${fmt(ty)}</text>`;
  for (const tx of niceTicks(xmin, xmax, 5)) g +=
    `<text x="${X(tx)}" y="${h - 14}" font-size="10" fill="#697386" text-anchor="middle">${fmt(tx)}</text>`;
  let dots = "";
  for (const p of pts) dots +=
    `<circle cx="${X(p.x).toFixed(1)}" cy="${Y(p.y).toFixed(1)}" r="4" ` +
    `fill="${p.color}" fill-opacity="0.85"><title>${esc(p.label)}: ` +
    `${xlabel}=${fmt(p.x)} ${ylabel}=${fmt(p.y)}</title></circle>`;
  return `<svg class="chart" width="${w}" height="${h}">` + g + dots +
    `<text x="${(w + mL) / 2}" y="${h - 2}" font-size="10" fill="#1a1f36" ` +
    `text-anchor="middle">${esc(xlabel)}</text>` +
    `<text x="12" y="${mT + 8}" font-size="10" fill="#1a1f36">${esc(ylabel)}</text></svg>`;
}
function parcoords(axes, rows) {
  // axes: [{name, min, max}]; rows: [{vals: [...], t (0 best..1 worst), label}]
  const w = Math.max(420, axes.length * 110), h = 230, mT = 24, mB = 14;
  const ax = i => 40 + i * (w - 80) / Math.max(axes.length - 1, 1);
  const Y = (a, v) => {
    const lo = a.min, hi = a.max === a.min ? a.min + 1 : a.max;
    return h - mB - (v - lo) / (hi - lo) * (h - mT - mB);
  };
  let g = "";
  axes.forEach((a, i) => {
    g += `<line x1="${ax(i)}" y1="${mT}" x2="${ax(i)}" y2="${h - mB}" stroke="#cfd7e0"/>` +
         `<text x="${ax(i)}" y="12" font-size="10" fill="#1a1f36" text-anchor="middle">${esc(a.name)}</text>` +
         `<text x="${ax(i)}" y="${mT - 2}" font-size="9" fill="#697386" text-anchor="middle">${fmt(a.max)}</text>` +
         `<text x="${ax(i)}" y="${h - 2}" font-size="9" fill="#697386" text-anchor="middle">${fmt(a.min)}</text>`;
  });
  let lines = "";
  for (const r of rows) {
    const pts = r.vals.map((v, i) => `${ax(i).toFixed(1)},${Y(axes[i], v).toFixed(1)}`).join(" ");
    lines += `<polyline fill="none" stroke="${heat(r.t)}" stroke-width="1.5" ` +
      `stroke-opacity="0.75" points="${pts}"><title>${esc(r.label)}</title></polyline>`;
  }
  return `<svg class="chart" width="${w}" height="${h}">` + g + lines + `</svg>`;
}
function toPts(events) {
  const pts = [];
  events.forEach((e, i) => {
    if (typeof e.metric === "number")
      pts.push([typeof e.step === "number" ? e.step : i, e.metric]);
  });
  return pts;
}
function legendHtml(series) {
  return series.map(s =>
    `<span class="legend" style="background:${s.color}"></span>` +
    `<span class="muted">${esc(s.label)}</span>`).join(" &nbsp; ");
}

// ---- detail tabs ----------------------------------------------------------
document.querySelectorAll("#tabs button").forEach(b => {
  b.onclick = () => { tab = b.dataset.tab; render(); };
});
async function renderOverview(r) {
  let html = `<table class="cmp"><tr><th></th><th>value</th></tr>`;
  for (const k of ["uuid", "kind", "created_at", "started_at", "finished_at"])
    if (r[k]) html += `<tr><td class="muted">${k}</td><td>${esc(r[k])}</td></tr>`;
  html += `</table>`;
  if (r.inputs && Object.keys(r.inputs).length)
    html += `<h3>Params</h3><pre>${esc(JSON.stringify(r.inputs, null, 2))}</pre>`;
  if (r.outputs)
    html += `<h3>Outputs</h3><pre>${esc(JSON.stringify(r.outputs, null, 2))}</pre>`;
  try {
    const sts = await j(`/api/v1/${project}/runs/${r.uuid}/statuses`);
    html += `<h3>Status timeline</h3><table class="cmp">`;
    for (const s of sts) html +=
      `<tr><td>${stBadge(s.type || s.status || "")}</td>` +
      `<td class="muted">${esc(s.created_at || "")}</td>` +
      `<td class="muted">${esc(s.reason || "")}</td></tr>`;
    html += `</table>`;
  } catch (e) {}
  return html;
}
function barChart(values, counts) {
  // histogram event: bin centers + counts -> SVG bars
  const w = 420, h = 160, mL = 46, mR = 8, mT = 8, mB = 20;
  if (!counts.length) return "";
  const cmax = Math.max(...counts), n = counts.length;
  const bw = (w - mL - mR) / n;
  let bars = "";
  counts.forEach((c, i) => {
    const bh = cmax > 0 ? c / cmax * (h - mT - mB) : 0;
    bars += `<rect x="${(mL + i * bw).toFixed(1)}" y="${(h - mB - bh).toFixed(1)}" ` +
      `width="${Math.max(bw - 1, 1).toFixed(1)}" height="${bh.toFixed(1)}" ` +
      `fill="#0b68cb" fill-opacity="0.8"><title>${fmt(values[i] ?? i)}: ${fmt(c)}</title></rect>`;
  });
  let g = `<text x="${mL - 4}" y="${mT + 8}" font-size="10" fill="#697386" text-anchor="end">${fmt(cmax)}</text>`;
  if (values.length) {
    g += `<text x="${mL}" y="${h - 6}" font-size="10" fill="#697386">${fmt(values[0])}</text>` +
         `<text x="${w - mR}" y="${h - 6}" font-size="10" fill="#697386" text-anchor="end">${fmt(values[values.length - 1])}</text>`;
  }
  return `<svg class="chart" width="${w}" height="${h}">${g}${bars}</svg>`;
}
const imgCache = {};  // url -> blob object URL (events are append-only,
                      // a path's bytes never change: cache forever so the
                      // 4s refresh neither refetches nor leaks blob URLs)
async function authedImg(url, imgId) {
  // <img src> can't carry the Authorization header: fetch -> blob URL
  try {
    if (!imgCache[url]) {
      const r = await fetch(url, {headers: hdrs()});
      if (!r.ok) return;
      imgCache[url] = URL.createObjectURL(await r.blob());
    }
    const el = document.getElementById(imgId);
    if (el) el.src = imgCache[url];
  } catch (e) {}
}
function isResourceMetric(n) { return /^(host_|tpu\\d*_)/.test(n); }
async function renderMetrics(r) {
  let html = "";
  try {
    const m = await j(`/api/v1/${project}/runs/${r.uuid}/metrics`);
    const names = Object.keys(m).sort();
    if (!names.length) return '<span class="muted">no metrics yet</span>';
    const chart = (name) => {
      const pts = toPts(m[name]);
      if (!pts.length) return "";
      const last = pts[pts.length - 1][1];
      return `<h3>${esc(name)} <span class="muted">last ${fmt(last)}</span></h3>` +
             lineChart([{label: name, color: COLORS[0], pts}], {});
    };
    for (const name of names.filter(n => !isResourceMetric(n)))
      html += chart(name);
    const res = names.filter(isResourceMetric);
    if (res.length) {
      // host/TPU telemetry (ResourceLogger) charts in its own section so
      // training curves stay uncluttered
      html += `<h2>Resources</h2>`;
      for (const name of res) html += chart(name);
    }
    // histogram events: latest-step distribution per name
    try {
      const hm = await j(`/api/v1/${project}/runs/${r.uuid}/events/histogram`);
      const hnames = Object.keys(hm).sort();
      if (hnames.length) html += `<h2>Histograms</h2>`;
      for (const name of hnames) {
        const evs = hm[name];
        const last = evs[evs.length - 1];
        const hg = last && last.histogram;
        if (!hg) continue;
        html += `<h3>${esc(name)} <span class="muted">step ${last.step ?? "-"}</span></h3>` +
                barChart(hg.values || [], hg.counts || []);
      }
    } catch (e) {}
    // image events: latest image per name (auth-fetched into blob URLs)
    try {
      const im = await j(`/api/v1/${project}/runs/${r.uuid}/events/image`);
      const inames = Object.keys(im).sort();
      if (inames.length) html += `<h2>Images</h2>`;
      inames.forEach((name, idx) => {
        const evs = im[name];
        const last = evs[evs.length - 1];
        const img = last && last.image;
        if (!img || !img.path) return;
        const iid = "im" + idx;  // index, not name: lossy-stripped names
                                 // ("attn_1"/"attn1") would collide
        html += `<h3>${esc(name)} <span class="muted">step ${last.step ?? "-"}</span></h3>` +
          `<img id="${iid}" style="max-width:480px;border:1px solid #e3e8ee;border-radius:4px"/>`;
        // defer until the html lands in the DOM (same trick as lineChart)
        setTimeout(() => authedImg(
          `/api/v1/${project}/runs/${r.uuid}/artifacts/file?path=` +
          encodeURIComponent(img.path), iid), 0);
      });
    } catch (e) {}
    // curve events (VERDICT weak #7): latest x/y curve per name (roc, pr,
    // calibration ...) as a real line chart
    try {
      const cm = await j(`/api/v1/${project}/runs/${r.uuid}/events/curve`);
      const cnames = Object.keys(cm).sort();
      if (cnames.length) html += `<h2>Curves</h2>`;
      for (const name of cnames) {
        const evs = cm[name];
        const last = evs[evs.length - 1];
        const cv = last && last.curve;
        if (!cv || !cv.x || !cv.y) continue;
        const pts = cv.x.map((x, i) => [x, cv.y[i]]);
        html += `<h3>${esc(name)} <span class="muted">step ${last.step ?? "-"}` +
                `${cv.annotation ? " · " + esc(cv.annotation) : ""}</span></h3>` +
                lineChart([{label: name, color: COLORS[4], pts}], {});
      }
    } catch (e) {}
    // confusion events: latest matrix per name, heat-shaded cells
    try {
      const fm = await j(`/api/v1/${project}/runs/${r.uuid}/events/confusion`);
      const fnames = Object.keys(fm).sort();
      if (fnames.length) html += `<h2>Confusion matrices</h2>`;
      for (const name of fnames) {
        const evs = fm[name];
        const last = evs[evs.length - 1];
        const cf = last && last.confusion;
        if (!cf || !cf.z) continue;
        const zmax = Math.max(...cf.z.flat(), 1e-9);
        html += `<h3>${esc(name)} <span class="muted">step ${last.step ?? "-"}</span></h3>` +
          `<table class="cmp" style="width:auto"><tr><th></th>` +
          (cf.x || []).map(c => `<th>${esc(c)}</th>`).join("") + `</tr>`;
        cf.z.forEach((row, i) => {
          html += `<tr><th>${esc((cf.y || [])[i] ?? i)}</th>` + row.map(v => {
            const a = (v / zmax * 0.85).toFixed(3);
            return `<td style="background:rgba(11,104,203,${a});` +
              `color:${v / zmax > 0.55 ? "#fff" : "#1a1f36"}">${fmt(v)}</td>`;
          }).join("") + `</tr>`;
        });
        html += `</table>`;
      }
    } catch (e) {}
  } catch (e) { html = `<span class="muted">${esc(e)}</span>`; }
  return html;
}
// ---- timeline waterfall ---------------------------------------------------
async function renderTimeline(r) {
  let t;
  try { t = await j(`/api/v1/${project}/runs/${r.uuid}/timeline`); }
  catch (e) { return `<span class="muted">${esc(e)}</span>`; }
  const spans = t.spans || [];
  if (!spans.length) return '<span class="muted">no spans yet</span>';
  const tmin = Math.min(...spans.map(s => s.start));
  const tmax = Math.max(...spans.map(s => s.end), tmin + 1e-6);
  const W = 680, LBL = 180, ROW = 22, PAD = 6;
  const X = v => LBL + (v - tmin) / (tmax - tmin) * (W - LBL - 64);
  const col = p => p === "pod" ? "#18794e" : "#0b68cb";
  const h = PAD * 2 + spans.length * ROW + 18;
  const dfmt = d => d >= 1 ? d.toFixed(2) + "s" : (d * 1000).toFixed(1) + "ms";
  let g = "";
  spans.forEach((s, i) => {
    const y = PAD + i * ROW;
    const x1 = X(s.start), x2 = Math.max(X(s.end), x1 + 2);
    const dur = dfmt(s.duration_s);
    g += `<text x="4" y="${y + 14}" font-size="11" fill="#1a1f36">${esc(s.name)}</text>` +
      `<rect x="${x1.toFixed(1)}" y="${y + 4}" width="${(x2 - x1).toFixed(1)}" ` +
      `height="12" rx="2" fill="${col(s.process)}" fill-opacity="0.85">` +
      `<title>${esc(s.name)} [${esc(s.process)}] ${dur}` +
      `${s.meta && s.meta.reason ? " — " + esc(s.meta.reason) : ""}</title></rect>` +
      `<text x="${(x2 + 4).toFixed(1)}" y="${y + 14}" font-size="10" ` +
      `fill="#697386">${dur}</text>`;
  });
  g += `<text x="${LBL}" y="${h - 4}" font-size="10" fill="#697386">0</text>` +
    `<text x="${W - 8}" y="${h - 4}" font-size="10" fill="#697386" ` +
    `text-anchor="end">${dfmt(tmax - tmin)}</text>`;
  return `<div class="muted">trace <code>${esc(t.trace_id)}</code> &nbsp; ` +
    `<span class="legend" style="background:#0b68cb"></span>control-plane &nbsp;` +
    `<span class="legend" style="background:#18794e"></span>pod</div>` +
    `<svg class="chart" width="${W}" height="${h}">${g}</svg>`;
}
let artPath = "";
function isTrace(name) {
  return /\\.trace\\.json(\\.gz)?$|\\.pb$|perfetto|xplane/.test(name);
}
async function renderArtifacts(r) {
  let html = "";
  try {
    const t = await j(`/api/v1/${project}/runs/${r.uuid}/artifacts/tree` +
                      (artPath ? `?path=${encodeURIComponent(artPath)}` : ""));
    const crumbs = ["<a data-p=''>artifacts</a>"];
    let acc = "";
    for (const part of (artPath ? artPath.split("/") : [])) {
      acc = acc ? acc + "/" + part : part;
      crumbs.push(`<a data-p="${esc(acc)}">${esc(part)}</a>`);
    }
    html += `<div class="crumb">${crumbs.join(" / ")}</div><table class="cmp">`;
    for (const d of t.dirs)
      html += `<tr class="dirrow"><td class="dir" data-p="` +
        esc(artPath ? artPath + "/" + d : d) + `">📁 ${esc(d)}</td><td></td></tr>`;
    for (const f of t.files) {
      const rel = artPath ? artPath + "/" + f.name : f.name;
      const href = `/api/v1/${project}/runs/${r.uuid}/artifacts/file?path=` +
                   encodeURIComponent(rel);
      html += `<tr${isTrace(f.name) ? ' class="trace"' : ""}><td class="file">` +
        `<a href="${href}" download>${esc(f.name)}</a>` +
        `${isTrace(f.name) ? ' <span class="muted">(profile trace)</span>' : ""}</td>` +
        `<td class="muted">${(f.size / 1024).toFixed(1)} KB</td></tr>`;
    }
    html += `</table>`;
  } catch (e) { html = `<span class="muted">no artifacts</span>`; }
  return html;
}
let logQuery = "";
async function renderLogs(r) {
  const logs = await text(`/api/v1/${project}/runs/${r.uuid}/logs?tail=2000`);
  if (!logs) return '<span class="muted">no logs yet</span>';
  let lines = logs.split("\\n");
  let note = "";
  if (logQuery) {
    const q = logQuery.toLowerCase();
    const kept = lines.filter(l => l.toLowerCase().includes(q));
    note = `<span class="muted">${kept.length}/${lines.length} lines</span>`;
    lines = kept;
  }
  const shown = lines.slice(-800);
  if (shown.length < lines.length)
    note += ` <span class="muted">(showing last ${shown.length})</span>`;
  // highlight on the RAW line, escaping per segment — running the query
  // regex over escaped text would match inside &lt;-style entities and
  // miss queries containing <, & or "
  const hi = (l) => {
    if (!logQuery) return esc(l);
    const re = new RegExp(logQuery.replace(/[.*+?^${}()|[\\]\\\\]/g, "\\\\$&"), "gi");
    let out = "", last = 0, mm;
    while ((mm = re.exec(l)) !== null) {
      out += esc(l.slice(last, mm.index)) + `<mark>${esc(mm[0])}</mark>`;
      last = mm.index + mm[0].length;
      if (mm.index === re.lastIndex) re.lastIndex++;  // zero-width guard
    }
    return out + esc(l.slice(last));
  };
  return `<div><input id="logQ" placeholder="search logs" value="${esc(logQuery)}"/> ${note}</div>` +
         `<pre>${shown.map(hi).join("\\n")}</pre>`;
}
function wireLogs() {
  const q = $("#logQ");
  if (!q) return;
  // blur before re-rendering: render() skips while the box is focused (the
  // auto-refresh guard), so Enter must drop focus first to take effect
  const go = () => { logQuery = q.value; q.blur(); render(); };
  q.onchange = go;
  q.onkeydown = (ev) => { if (ev.key === "Enter") go(); };
}
// ---- DAG graph ------------------------------------------------------------
const ST_COLORS = {succeeded: "#18794e", failed: "#cd2b31", running: "#0b68cb",
                   stopped: "#6c757d", skipped: "#6c757d"};
function dagOps(r) {
  const run = (((r.spec || {}).component || {}).run) || {};
  return run.kind === "dag" ? (run.operations || []) : null;
}
function opDeps(op) {
  // mirror the backend's edge sources (V1Dag.topological_order): explicit
  // dependencies + structured param refs ({"ref": "ops.train"}) +
  // template refs scoped INSIDE {{ }} braces — a literal string value
  // mentioning "ops.train" must not fabricate an edge
  const deps = new Set(op.dependencies || []);
  for (const p of Object.values(op.params || {})) {
    if (p && typeof p === "object" && typeof p.ref === "string" &&
        p.ref.startsWith("ops."))
      deps.add(p.ref.slice(4));
    if (typeof p === "string")
      for (const m of p.matchAll(/\\{\\{[^}]*?\\bops\\.([A-Za-z0-9_-]+)/g))
        deps.add(m[1]);
  }
  deps.delete(op.name);  // self-mentions must not loop the layering
  return [...deps];
}
async function renderGraph(r) {
  const ops = dagOps(r);
  if (!ops || !ops.length) return '<span class="muted">this DAG has no operations</span>';
  const kids = await j(`/api/v1/${project}/runs?pipeline_uuid=${r.uuid}&limit=500`);
  // the dag runner stamps meta.dag_op on every child — the exact key.
  // Name fallbacks cover manually-created children ("-{op}" suffix can
  // mis-match ops that are suffixes of one another, so it comes last)
  const childOf = (op) =>
    kids.find(k => k.meta && k.meta.dag_op === op) ||
    kids.find(k => k.name === op) ||
    kids.find(k => (k.name || "").endsWith("-" + op));
  // topological levels
  const level = {}, names = ops.map(o => o.name);
  const depMap = {};
  for (const op of ops) depMap[op.name] = opDeps(op).filter(d => names.includes(d));
  let changed = true, guard = 0;
  for (const n of names) level[n] = 0;
  while (changed && guard++ < 100) {
    changed = false;
    for (const n of names) {
      const want = Math.max(0, ...depMap[n].map(d => level[d] + 1));
      if (want !== level[n]) { level[n] = want; changed = true; }
    }
  }
  const cols = {};
  for (const n of names) (cols[level[n]] = cols[level[n]] || []).push(n);
  const nlevels = Object.keys(cols).length;
  const NW = 150, NH = 44, GX = 70, GY = 18, PAD = 12;
  const pos = {};
  Object.entries(cols).forEach(([lv, ns]) => {
    ns.forEach((n, i) => {
      pos[n] = {x: PAD + lv * (NW + GX), y: PAD + i * (NH + GY)};
    });
  });
  const w = PAD * 2 + nlevels * NW + (nlevels - 1) * GX;
  const h = PAD * 2 + Math.max(...Object.values(cols).map(c => c.length))
            * (NH + GY) - GY;
  let edges = "";
  for (const n of names) for (const d of depMap[n]) {
    const a = pos[d], b = pos[n];
    const x1 = a.x + NW, y1 = a.y + NH / 2, x2 = b.x, y2 = b.y + NH / 2;
    edges += `<path d="M${x1},${y1} C${x1 + GX / 2},${y1} ${x2 - GX / 2},${y2} ` +
      `${x2},${y2}" fill="none" stroke="#9aa5b1" stroke-width="1.5" ` +
      `marker-end="url(#arr)"/>`;
  }
  let nodes = "";
  for (const n of names) {
    const k = childOf(n);
    const st = k ? k.status : "created";
    const color = ST_COLORS[st] || "#b98900";
    const p = pos[n];
    nodes += `<g class="dagnode" data-u="${k ? k.uuid : ""}" style="cursor:pointer">` +
      `<rect x="${p.x}" y="${p.y}" width="${NW}" height="${NH}" rx="6" ` +
      `fill="#fff" stroke="${color}" stroke-width="2"/>` +
      `<text x="${p.x + 10}" y="${p.y + 18}" font-size="12" ` +
      `fill="#1a1f36" font-weight="600">${esc(n)}</text>` +
      `<text x="${p.x + 10}" y="${p.y + 34}" font-size="10" ` +
      `fill="${color}">${esc(st)}</text></g>`;
  }
  return `<svg class="chart" width="${w}" height="${h}">` +
    `<defs><marker id="arr" markerWidth="8" markerHeight="8" refX="7" ` +
    `refY="3" orient="auto"><path d="M0,0 L7,3 L0,6 z" fill="#9aa5b1"/>` +
    `</marker></defs>` + edges + nodes + `</svg>` +
    `<div class="muted">click a node to open its run</div>`;
}
function wireGraph() {
  document.querySelectorAll("#dBody .dagnode").forEach(el => {
    el.onclick = () => {
      if (el.dataset.u) { selected = el.dataset.u; tab = "overview"; render(); }
    };
  });
}
let sweepMetric = null, sweepParam = null, sweepMax = false;
async function renderSweep(r) {
  const LIM = 2000;
  const kids = await j(`/api/v1/${project}/runs?pipeline_uuid=${r.uuid}&limit=${LIM}`);
  if (!kids.length) return '<span class="muted">no child runs yet</span>';
  const truncated = kids.length >= LIM;
  const num = v => typeof v === "number" && isFinite(v);
  const pkeys = [...new Set(kids.flatMap(k => Object.keys(k.inputs || {})
                  .filter(p => num((k.inputs || {})[p]))))].sort();
  const mkeys = [...new Set(kids.flatMap(k => Object.keys(k.outputs || {})
                  .filter(m => num((k.outputs || {})[m]))))].sort();
  if (!mkeys.length)
    return `<span class="muted">${kids.length} children, no numeric outputs yet</span>`;
  if (!mkeys.includes(sweepMetric))
    sweepMetric = mkeys.includes("loss") ? "loss" : mkeys[0];
  if (!pkeys.includes(sweepParam)) sweepParam = pkeys[0] || null;
  const done = kids.filter(k => num((k.outputs || {})[sweepMetric]));
  const vals = done.map(k => k.outputs[sweepMetric]);
  const lo = Math.min(...vals), hi = Math.max(...vals);
  const tOf = v => { // 0 = best
    const t = hi === lo ? 0 : (v - lo) / (hi - lo);
    return sweepMax ? 1 - t : t;
  };
  const label = k => k.name || k.uuid.slice(0, 8);
  let html =
    (truncated ? `<div class="muted">&#9888; showing first ${LIM} children ` +
                 `only — leaderboard may be incomplete</div>` : "") +
    `<div class="muted">${kids.length} children, ${done.length} with ` +
    `<b>${esc(sweepMetric)}</b> &nbsp; metric ` +
    `<select id="swMetric">${mkeys.map(m =>
      `<option${m === sweepMetric ? " selected" : ""}>${esc(m)}</option>`).join("")}` +
    `</select> <label><input type="checkbox" id="swMax"${sweepMax ? " checked" : ""}/>` +
    ` higher is better</label></div>`;
  if (sweepParam && done.length) {
    html += `<h3>${esc(sweepMetric)} vs <select id="swParam">${pkeys.map(p =>
      `<option${p === sweepParam ? " selected" : ""}>${esc(p)}</option>`).join("")}` +
      `</select></h3>`;
    html += scatterChart(done
      .filter(k => num((k.inputs || {})[sweepParam]))
      .map(k => ({
        x: k.inputs[sweepParam], y: k.outputs[sweepMetric],
        label: label(k), color: heat(tOf(k.outputs[sweepMetric])),
      })), sweepParam, sweepMetric);
  }
  if (pkeys.length >= 1 && done.length) {
    const axes = pkeys.map(p => {
      const vs = done.map(k => (k.inputs || {})[p]).filter(num);
      return {name: p, min: Math.min(...vs), max: Math.max(...vs)};
    }).concat([{name: sweepMetric, min: lo, max: hi}]);
    const rows = done
      .filter(k => pkeys.every(p => num((k.inputs || {})[p])))
      .map(k => ({
        vals: pkeys.map(p => k.inputs[p]).concat([k.outputs[sweepMetric]]),
        t: tOf(k.outputs[sweepMetric]), label: label(k),
      }));
    html += `<h3>Parallel coordinates <span class="muted">green = best</span></h3>` +
            parcoords(axes, rows);
  }
  // crash-safe sweep meta (ISSUE 19): the tuner stamps every trial with
  // (trial_index, rung, parent_trial) — durable STORE truth, so the rung
  // ladder and PBT lineage render from the listing alone
  const sweepKids = kids.filter(k => k.meta && num(k.meta.trial_index));
  if (sweepKids.length) {
    const rungs = [...new Set(sweepKids.map(k => k.meta.rung || 0))].sort((a, b) => a - b);
    if (rungs.length > 1 || (rungs.length === 1 && rungs[0] > 0)) {
      html += `<h3>Rungs</h3><table class="cmp"><tr><th>rung</th>` +
        `<th>trials</th><th>done</th><th>best ${esc(sweepMetric)}</th></tr>`;
      for (const rg of rungs) {
        const at = sweepKids.filter(k => (k.meta.rung || 0) === rg);
        const fin = at.filter(k => num((k.outputs || {})[sweepMetric]))
                      .map(k => k.outputs[sweepMetric]);
        const best = fin.length
          ? (sweepMax ? Math.max(...fin) : Math.min(...fin)) : null;
        html += `<tr><td>${rg}</td><td>${at.length}</td><td>${fin.length}</td>` +
          `<td>${best === null ? "" : fmt(best)}</td></tr>`;
      }
      html += `</table>`;
    }
  }
  const byIndex = {};
  for (const k of sweepKids) byIndex[k.meta.trial_index] = k;
  const trialCell = k => {
    if (!(k.meta && num(k.meta.trial_index))) return "";
    let cell = `#${k.meta.trial_index}`;
    if (num(k.meta.rung) && k.meta.rung > 0) cell += ` r${k.meta.rung}`;
    if (k.meta.parent_trial != null) {
      // PBT exploit lineage: forked from the parent's checkpoint
      const par = Object.values(byIndex).find(p => p.uuid === k.meta.parent_trial);
      cell += ` <span class="muted" title="forked from ` +
        `${esc(par ? label(par) : String(k.meta.parent_trial).slice(0, 8))}` +
        `" style="cursor:help">&#8618;</span>`;
    }
    return cell;
  };
  const hasTrials = sweepKids.length > 0;
  const ranked = [...done].sort((a, b) =>
    sweepMax ? b.outputs[sweepMetric] - a.outputs[sweepMetric]
             : a.outputs[sweepMetric] - b.outputs[sweepMetric]);
  html += `<h3>Leaderboard</h3><table class="cmp"><tr><th>#</th><th>run</th>` +
    (hasTrials ? `<th>trial</th>` : "") +
    `<th>status</th><th>${esc(sweepMetric)}</th>` +
    pkeys.map(p => `<th>${esc(p)}</th>`).join("") + `</tr>`;
  ranked.slice(0, 10).forEach((k, i) => {
    html += `<tr class="${i === 0 ? "winner" : ""} krow" data-u="${k.uuid}">` +
      `<td>${i + 1}</td><td>${esc(label(k))}</td>` +
      (hasTrials ? `<td class="muted">${trialCell(k)}</td>` : "") +
      `<td>${stBadge(k.status)}</td>` +
      `<td>${fmt(k.outputs[sweepMetric])}</td>` +
      pkeys.map(p => `<td>${num((k.inputs || {})[p]) ? fmt(k.inputs[p]) : ""}</td>`).join("") +
      `</tr>`;
  });
  html += `</table>`;
  const pending = kids.filter(k => !num((k.outputs || {})[sweepMetric]));
  if (pending.length) {
    html += `<h3>In flight / no result</h3><table class="cmp">`;
    for (const k of pending) html +=
      `<tr class="krow" data-u="${k.uuid}"><td>${esc(label(k))}</td>` +
      `<td>${stBadge(k.status)}</td></tr>`;
    html += `</table>`;
  }
  return html;
}
function wireSweep() {
  const m = $("#swMetric"), p = $("#swParam"), x = $("#swMax");
  if (m) m.onchange = () => { sweepMetric = m.value; render(); };
  if (p) p.onchange = () => { sweepParam = p.value; render(); };
  if (x) x.onchange = () => { sweepMax = x.checked; render(); };
  document.querySelectorAll("#dBody .krow").forEach(el => {
    el.onclick = () => { selected = el.dataset.u; tab = "overview"; render(); };
  });
}
async function renderCompare(uuids) {
  const runs = await Promise.all(
    uuids.map(u => j(`/api/v1/${project}/runs/${u}`)));
  $("#dTitle").textContent = `Compare ${runs.length} runs`;
  $("#tabs").style.display = "none";
  const label = r => r.name || r.uuid.slice(0, 8);
  let html = `<h3>Runs</h3><table class="cmp"><tr><th></th><th>run</th>` +
             `<th>status</th><th>params</th><th>outputs</th></tr>`;
  runs.forEach((r, i) => {
    html += `<tr><td><span class="legend" style="background:${COLORS[i % COLORS.length]}">` +
      `</span></td><td>${esc(label(r))}</td><td>${stBadge(r.status)}</td>` +
      `<td><pre style="max-height:80px">${esc(JSON.stringify(r.inputs || {}))}</pre></td>` +
      `<td><pre style="max-height:80px">${esc(JSON.stringify(r.outputs || {}))}</pre></td></tr>`;
  });
  html += `</table>`;
  const all = await Promise.all(
    uuids.map(u => j(`/api/v1/${project}/runs/${u}/metrics`).catch(() => ({}))));
  const names = [...new Set(all.flatMap(m => Object.keys(m)))].sort();
  for (const name of names) {
    const series = [];
    runs.forEach((r, i) => {
      const pts = toPts(all[i][name] || []);
      if (pts.length) series.push(
        {label: label(r), color: COLORS[i % COLORS.length], pts});
    });
    if (!series.length) continue;
    html += `<h3>${esc(name)}</h3><div>${legendHtml(series)}</div>` +
            lineChart(series, {});
  }
  $("#dBody").innerHTML = html;
}
async function render() {
  if (compare && compare.length >= 2) return renderCompare(compare);
  if (!selected) return;
  // don't clobber an in-progress log search on the 4s auto-refresh
  if (document.activeElement && document.activeElement.id === "logQ") return;
  const r = await j(`/api/v1/${project}/runs/${selected}`);
  $("#dTitle").innerHTML = `${esc(r.name || r.uuid)} ${stBadge(r.status)}`;
  $("#tabs").style.display = "";
  // own children query, not the status-filtered runCache: a finished
  // pipeline viewed under a "running" filter must keep its Sweep tab
  let hasKids = childrenOf(r.uuid).length > 0;
  if (!hasKids) {
    try {
      hasKids = (await j(
        `/api/v1/${project}/runs?pipeline_uuid=${r.uuid}&limit=1`)).length > 0;
    } catch (e) {}
  }
  $("#sweepTab").style.display = hasKids ? "" : "none";
  if (tab === "sweep" && !hasKids) tab = "overview";
  const dops = dagOps(r);
  const isDag = !!(dops && dops.length);
  $("#graphTab").style.display = isDag ? "" : "none";
  if (tab === "graph" && !isDag) tab = "overview";
  document.querySelectorAll("#tabs button").forEach(b =>
    b.classList.toggle("active", b.dataset.tab === tab));
  let html = "";
  if (tab === "overview") html = await renderOverview(r);
  else if (tab === "metrics") html = await renderMetrics(r);
  else if (tab === "timeline") html = await renderTimeline(r);
  else if (tab === "sweep") html = await renderSweep(r);
  else if (tab === "graph") html = await renderGraph(r);
  else if (tab === "artifacts") html = await renderArtifacts(r);
  else if (tab === "logs") html = await renderLogs(r);
  $("#dBody").innerHTML = html || '<span class="muted">no data yet</span>';
  if (tab === "sweep") wireSweep();
  if (tab === "graph") wireGraph();
  if (tab === "logs") wireLogs();
  if (tab === "artifacts") {
    document.querySelectorAll("#dBody .dir, #dBody .crumb a").forEach(el => {
      el.onclick = () => { artPath = el.dataset.p || ""; render(); };
    });
  }
}
// tenant/usage panel (ISSUE 15): quota rows with live chips-in-use bars.
// Scoped tokens get 403 on the admin-shaped route — the panel just hides.
async function loadQuotas() {
  const el = $("#quotas");
  try {
    const qs = await j("/api/v1/quotas");
    if (!qs.length) { el.innerHTML = ""; return; }
    el.innerHTML = `<b>Tenant quotas</b> ` + qs.map(q => {
      const used = q.in_use || 0;
      const pct = q.chips ? Math.min(100, Math.round(100 * used / q.chips)) : 0;
      const over = q.chips && used >= q.chips ? "background:#cd2b31" : "";
      return `<span class="quota"><span class="qname">${esc(q.tenant)}` +
        `</span> ${used}/${q.chips} chips` +
        `<span class="qbar"><span style="width:${pct}%;${over}"></span>` +
        `</span></span>`;
    }).join("");
  } catch (e) { el.innerHTML = ""; }
}
// federation panel (ISSUE 16): registered clusters with live health —
// a LOST cluster (lapsed health lease) shows loudly while its runs
// re-place onto survivors. Hidden on single-cluster deployments.
async function loadClusters() {
  const el = $("#clusters");
  try {
    const cs = await j("/api/v1/clusters");
    if (!cs.length) { el.innerHTML = ""; return; }
    el.innerHTML = `<b>Clusters</b> ` + cs.map(c => {
      const mark = c.healthy
        ? `<span style="color:#30a46c">●</span>`
        : `<span style="color:#cd2b31" title="health lease lapsed: ` +
          `runs re-placing onto survivors">● LOST</span>`;
      return `<span class="quota"><span class="qname">${esc(c.name)}` +
        `</span> ${mark} ${esc(c.chip_type || "?")}` +
        `×${c.capacity || 0}` +
        (c.region ? ` <span class="muted">${esc(c.region)}</span>` : "") +
        `</span>`;
    }).join("");
  } catch (e) { el.innerHTML = ""; }
}
// alerts panel (ISSUE 20): SLO alert rows, firing-first, each with a
// burn-rate sparkline from the ring-buffer history endpoint. Resolved
// rows drop out; an empty table (or a scoped token's 403) hides the
// panel entirely — most dashboards should never see it.
const SPARK = "▁▂▃▄▅▆▇█";
function sparkline(points) {
  const tail = (points || []).slice(-24);
  const vs = tail.map(p => p[1]).filter(v => typeof v === "number");
  if (!vs.length) return "";
  const lo = Math.min(...vs), hi = Math.max(...vs);
  return tail.map(p => {
    if (typeof p[1] !== "number") return " ";
    const i = hi > lo
      ? Math.round((p[1] - lo) / (hi - lo) * (SPARK.length - 1)) : 0;
    return SPARK[i];
  }).join("");
}
async function loadAlerts() {
  const el = $("#alerts");
  try {
    const rows = ((await j("/api/v1/alerts")).alerts || [])
      .filter(a => a.state !== "resolved");
    if (!rows.length) { el.innerHTML = ""; return; }
    // ONE history fetch covers every alert: burn gauges share a family
    // and differ only by their {slo=...} label
    let burns = [];
    try {
      burns = (await j("/api/v1/metrics/history" +
                       "?family=polyaxon_slo_burn_rate&range=3600")).series || [];
    } catch (e) {}
    el.innerHTML = `<b>Alerts</b> ` + rows.map(a => {
      const s = burns.find(b => (b.labels || {}).slo === a.slo);
      const spark = sparkline(s && s.points);
      const mark = a.state === "firing"
        ? `<span style="color:#cd2b31" title="${esc(a.reason || "")}">` +
          `&#9679; FIRING</span>`
        : `<span style="color:#b98900" title="${esc(a.reason || "")}">` +
          `&#9679; pending</span>`;
      return `<span class="quota"><span class="qname">${esc(a.name)}` +
        `</span> ${mark}` +
        (a.severity ? ` ${esc(a.severity)}` : "") +
        (typeof a.value === "number" ? ` burn ${a.value.toFixed(2)}` : "") +
        (spark ? ` <span style="font-family:monospace">${spark}</span>` : "") +
        `</span>`;
    }).join("");
  } catch (e) { el.innerHTML = ""; }
}
async function refresh() {
  try { await loadProjects(); await loadRuns(); await loadQuotas();
        await loadClusters(); await loadAlerts();
        if (selected || compare) await render(); }
  catch (e) { $("#count").textContent = String(e); }
  // the stream subscribes per-project; a project picked/switched after
  // the subscription re-anchors it (first load subscribes before any
  // project is known, so this fires exactly once at startup too)
  if (es && esProject !== project) connectStream();
}
// ---- live updates (ISSUE 14) ----------------------------------------------
// The dashboard subscribes to the SSE change feed and applies run deltas
// in place: after the initial load a steady-state session issues ZERO
// periodic re-list calls. Polling survives only as the fallback — when
// EventSource is missing (feature-detected) or the stream keeps failing.
let es = null, esFails = 0, pollTimer = null, esRetryTimer = null;
const POLL_MS = 4000;
function startPolling() {
  if (!pollTimer) pollTimer = setInterval(refresh, POLL_MS);
}
function stopPolling() {
  if (pollTimer) { clearInterval(pollTimer); pollTimer = null; }
}
let tableTimer = null, detailTimer = null;
function scheduleTable() {  // coalesce bursts of deltas into one render
  if (tableTimer) return;
  tableTimer = setTimeout(() => { tableTimer = null; renderRunsTable(); }, 250);
}
function scheduleDetail() { // live log tail / timeline / metrics refresh
  if (detailTimer) return;
  detailTimer = setTimeout(() => { detailTimer = null; render(); }, 1000);
}
// ALL deltas (runs, deletes, heartbeats) that race an in-flight listing
// are BUFFERED and re-applied after the snapshot lands — a list response
// older than a just-applied delta must not roll the row back (for a
// DELETE the ghost row would otherwise persist forever: no further
// event for a deleted run ever arrives to correct it)
let listInFlight = false, pendingDeltas = [];
function replayDeltas() {
  const replay = pendingDeltas; pendingDeltas = [];
  for (const [kind, d] of replay) {
    if (kind === "run") applyRunDelta(d);
    else if (kind === "delete") onRunDelete(d);
    else onHeartbeat(d);
  }
}
function onRunDelta(r) {
  if (r.project !== project) return;
  if (listInFlight) { pendingDeltas.push(["run", r]); return; }
  applyRunDelta(r);
}
// filtered views re-list (throttled, EVENT-driven — still zero periodic
// calls) whenever a delta may change membership: whether an off-page run
// entered or left the filter is unknowable client-side, and guessing
// diverges the count permanently now that polling is dead
let relistTimer = null;
function scheduleRelist() {
  if (relistTimer) return;
  relistTimer = setTimeout(() => { relistTimer = null; loadRuns(); }, 1500);
}
function applyRunDelta(r) {
  const f = $("#stFilter").value;
  const i = runCache.findIndex(x => x.uuid === r.uuid);
  if (f) {
    if (i >= 0 && r.status === f) {
      runCache[i] = r; scheduleTable();       // in-place, still matching
    } else if (i >= 0 || r.status === f) {
      scheduleRelist();                        // membership changed
    }
  } else if (i >= 0) {
    runCache[i] = r; scheduleTable();
  } else if (r.status === "created") {
    // only a CREATE is a new row; a transition/output-merge of an
    // off-page run must neither fabricate a top-of-table entry nor
    // inflate the total (its page re-renders when navigated to)
    if (page === 0) {
      runCache.unshift(r);
      if (runCache.length > PAGE) runCache.pop();
      scheduleTable();
    }
    runTotal++; $("#count").textContent = `${runTotal} runs`;
  }
  if (selected === r.uuid) scheduleDetail();
}
function onRunDelete(d) {
  // delete events carry their project; another tenant's delete must not
  // move this project's count (and an unknown-project delete only acts
  // when the row is actually in the cache)
  if (d.project && d.project !== project) return;
  if (listInFlight) { pendingDeltas.push(["delete", d]); return; }
  const f = $("#stFilter").value;
  const i = runCache.findIndex(x => x.uuid === d.uuid);
  if (i >= 0) { runCache.splice(i, 1); scheduleTable(); }
  if (f) {
    // whether the DELETED run matched the filter is unknowable for
    // off-page rows — re-list (throttled, event-driven) for any
    // same-project delete instead of guessing the count
    if (i >= 0 || d.project === project) scheduleRelist();
  } else if (d.project === project || i >= 0) {
    if (runTotal > 0) { runTotal--; $("#count").textContent = `${runTotal} runs`; }
    // a page-row delete under-fills the visible page while off-page
    // rows exist — slide the next row in (event-driven re-list)
    if (i >= 0 && runTotal >= PAGE) scheduleRelist();
  }
  if (selected === d.uuid) { selected = null; $("#dTitle").textContent = "Select a run"; }
}
function onHeartbeat(d) {
  if (listInFlight) { pendingDeltas.push(["heartbeat", d]); return; }
  const r = runCache.find(x => x.uuid === d.uuid);
  if (r) {
    r.heartbeat_age_s = 0;  // a fresh beat clears the zombie badge
    if (typeof d.step === "number") {
      if (r.heartbeat_step !== d.step) r.heartbeat_step_age_s = 0;
      r.heartbeat_step = d.step;
    }
    scheduleTable();
  }
  // heartbeats are the liveness tick of the selected run's pod: refresh
  // the log tail / timeline / metrics tabs without any interval polling
  if (selected === d.uuid &&
      ["logs", "timeline", "metrics", "overview"].includes(tab))
    scheduleDetail();
}
let helloTimer = null, esProject = null, alertTimer = null;
function connectStream() {
  if (!window.EventSource) { refresh(); startPolling(); return; }
  if (es) { es.close(); es = null; }
  const t = tokenBox.value;
  // subscribe scoped to the selected project: an unfiltered stream
  // would ship every tenant's heartbeat ticks to every open tab (the
  // hub filters server-side; the handlers' project guards stay as
  // defense in depth). refresh() reconnects when the project changes.
  esProject = project;
  const qs = [];
  if (project) qs.push("project=" + encodeURIComponent(project));
  if (t) qs.push("access_token=" + encodeURIComponent(t));
  es = new EventSource("/api/v1/streams/runs" +
                       (qs.length ? "?" + qs.join("&") : ""));
  // no-hello watchdog: a stream that CONNECTS but delivers nothing (a
  // buffering proxy — the exact case the poll fallback exists for)
  // never fires onerror; don't leave the page blank waiting for it
  if (helloTimer) clearTimeout(helloTimer);
  helloTimer = setTimeout(() => { refresh(); startPolling(); }, 5000);
  // SUBSCRIBE-then-list: hello anchors the stream at the hub's head,
  // and only then is the snapshot fetched — the other order loses any
  // delta committed between the list response and the registration
  // (deltas racing the fetch are buffered + replayed by loadRuns)
  es.addEventListener("hello", () => {
    if (helloTimer) { clearTimeout(helloTimer); helloTimer = null; }
    esFails = 0; stopPolling(); refresh();
  });
  es.addEventListener("run", ev => { esFails = 0; onRunDelta(JSON.parse(ev.data)); });
  es.addEventListener("delete", ev => onRunDelete(JSON.parse(ev.data)));
  es.addEventListener("heartbeat", ev => onHeartbeat(JSON.parse(ev.data)));
  // alert transitions (ISSUE 20) are rare fleet-scoped events: re-fetch
  // the panel (one small GET) instead of patching state client-side —
  // the table is tiny and the fetch dedups any burst via the coalescer
  es.addEventListener("alert", () => {
    if (alertTimer) return;
    alertTimer = setTimeout(() => { alertTimer = null; loadAlerts(); }, 250);
  });
  es.addEventListener("resync", () => {
    // an epoch rollover / store failover invalidated our position: full
    // resync — subscribe FRESH (a reconnect carrying the stale
    // Last-Event-ID would only earn a 410); the new hello re-lists
    es.close(); es = null;
    connectStream();
  });
  // "evicted" needs no handler: the server closes after it and the
  // native EventSource reconnect carries Last-Event-ID — the hub
  // replays what the bounded buffer dropped, loss-free
  es.onerror = () => {
    // repeated failures (server gone, proxy buffering, auth): fall back
    // to interval polling, and keep probing the stream in the background
    if (++esFails >= 3 && es) {
      es.close(); es = null;
      if (helloTimer) { clearTimeout(helloTimer); helloTimer = null; }
      refresh();  // don't leave a blank page waiting for the first tick
      startPolling();
      if (!esRetryTimer) esRetryTimer = setTimeout(() => {
        esRetryTimer = null; connectStream();
      }, 60000);
    }
  };
}
// client-side badge aging: zombie/stalled suspicion is an AGE crossing a
// threshold, and with polling dead nothing else moves the clock — a pod
// that dies silently emits no events at all. Ages advance locally
// between deltas (any fresh heartbeat/run event re-stamps them).
const AGE_MS = 15000;
setInterval(() => {
  let crossed = false;
  for (const r of runCache) {
    if (!["starting", "running"].includes(r.status)) continue;
    for (const k of ["heartbeat_age_s", "heartbeat_step_age_s"]) {
      if (typeof r[k] !== "number") continue;
      const before = r[k];
      r[k] += AGE_MS / 1000;
      const th = k === "heartbeat_age_s" ? 60 : 120;
      if (before <= th && r[k] > th) crossed = true;
    }
  }
  if (crossed) scheduleTable();
}, AGE_MS);
connectStream();  // hello triggers the initial refresh()
</script>
</body>
</html>
"""
