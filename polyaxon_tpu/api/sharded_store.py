"""Sharded server-backed store (ISSUE 18 tentpole): K independent
SQLite backends behind the single-store verb surface.

Every control-plane write used to serialize through ONE SQLite writer
lock. :class:`ShardedStore` partitions the run space by the same
``crc32(uuid) % K`` hash the agents already use (:func:`shard_index`),
so each shard is a full :class:`~polyaxon_tpu.api.store.Store` — its own
writer lock, its own commit-ordered ``change_seq`` changelog, its own
epoch/fencing/snapshot machinery — and N agents stop convoying on one
lock. The router keeps TODAY'S contract for every consumer:

**Composite feed tokens.** Consumers of the change feed (SSE watchers,
``?since=`` pollers, ``ReplicatedStandby``) compare and propagate
INTEGER tokens. The stitched feed therefore packs the per-shard cursor
vector into one integer — shard i's seq in bit field
``[40*i, 40*(i+1))`` — and qualifies it with the SUM of the per-shard
epochs. Each stitched event advances exactly one component, so tokens
stay strictly monotone along the feed; any single shard promoting
changes the epoch sum, so a pre-failover cursor is deterministically
rejected (410) exactly like today. 40 bits per shard is ~10^12 writes
per shard — decades at control-plane rates — and Python ints carry the
K*40-bit composite losslessly (tokens travel as strings; the JSON
``seq`` fields are arbitrary-precision for Python clients).

**Stitching.** :meth:`get_changelog` k-way-merges the per-shard tails by
``(created_at, shard_index)`` — deterministic for a given cursor,
per-shard seq order preserved (within a shard, ``created_at`` is stamped
under the writer lock, so the merge key respects seq order modulo a
wall-clock step; cross-shard ordering is by stamp, same-process clocks).
Every emitted record is re-sequenced to the composite cursor AFTER
consuming it and carries ``shard``/``shard_seq``/``shard_epoch`` so
:meth:`apply_changelog` can demux a stitched tail back into per-shard
replays — a ``ReplicatedStandby`` whose target is another ShardedStore
replicates through the stitched feed unchanged (HTTP or in-process).

**Routing.** Run-scoped verbs go to the owning shard; ``create_runs`` /
``transition_many`` split into per-shard transactions (PR 6's per-shard
sub-batch fencing semantics: a rejected sub-batch fails alone);
``list_runs``/``count_runs`` merge keyset pages across shards;
projects/tokens/quotas/clusters/config and presence leases live on the
designated META shard (backend 0 — which also owns its 1/K of the run
space). ``shard-<i>`` lease rows live IN backend i, so the lifecycle
fence check stays atomic with the guarded write (a run's store shard IS
its agent shard once the fleet adopts this store's claimed
``num_shards``). A fenced write whose lease lives on a DIFFERENT
backend (e.g. a quota write fenced by a shard lease) is verified against
the lease's home backend and then stripped: stale callers are still
rejected, but the check is no longer in the guarded write's transaction
— see docs/RESILIENCE.md for the honest scope of that fallback.
"""

from __future__ import annotations

import os
import threading
import uuid as uuid_mod
from typing import Any, Optional

from .store import (
    CompactedLogError,
    StaleEpochError,
    Store,
    StoreBackend,
    shard_index,
)

#: bits per shard in the composite feed token (matches EPOCH_STRIDE's
#: 40-bit seq field in lease fencing tokens: ~10^12 writes per shard)
SHARD_SEQ_BITS = 40
SHARD_SEQ_MASK = (1 << SHARD_SEQ_BITS) - 1


def pack_seqs(seqs: list) -> int:
    """Per-shard seq vector -> one composite integer (shard i in bit
    field ``[40*i, 40*(i+1))``). Strictly monotone under single-component
    advances, which is what makes the stitched feed's tokens comparable
    with plain ``<`` by every existing consumer."""
    v = 0
    for i, s in enumerate(seqs):
        s = int(s)
        if s < 0 or s > SHARD_SEQ_MASK:
            raise ValueError(f"shard seq {s} out of the 40-bit field")
        v |= s << (SHARD_SEQ_BITS * i)
    return v


def unpack_seqs(v: int, num_shards: int) -> list[int]:
    """Composite integer -> per-shard seq vector. Values <= 0 decode to
    the all-zeros vector (the bootstrap cursor)."""
    v = int(v)
    if v <= 0:
        return [0] * num_shards
    return [(v >> (SHARD_SEQ_BITS * i)) & SHARD_SEQ_MASK
            for i in range(num_shards)]


def _run_scoped(name: str):
    """Route a run-scoped verb to the uuid's owning shard, re-homing any
    fence first (same-shard fences — the lifecycle hot path — pass
    through untouched and stay transaction-atomic)."""

    def _verb(self, run_uuid: str, *a: Any, **kw: Any) -> Any:
        target = self._shard_of(run_uuid)
        if kw.get("fence") is not None:
            kw["fence"] = self._split_fence(target, kw["fence"])
        return getattr(target, name)(run_uuid, *a, **kw)

    _verb.__name__ = name
    _verb.__qualname__ = f"ShardedStore.{name}"
    _verb.__doc__ = f"Routed to the run's owning shard: Store.{name}."
    return _verb


def _meta_scoped(name: str):
    """Route a control-plane verb (projects, tokens, quotas, clusters,
    config) to the meta shard, re-homing any fence first."""

    def _verb(self, *a: Any, **kw: Any) -> Any:
        if kw.get("fence") is not None:
            kw["fence"] = self._split_fence(self._meta, kw["fence"])
        return getattr(self._meta, name)(*a, **kw)

    _verb.__name__ = name
    _verb.__qualname__ = f"ShardedStore.{name}"
    _verb.__doc__ = f"Routed to the meta shard: Store.{name}."
    return _verb


class ShardedStore(StoreBackend):
    """K :class:`Store` backends behind the single-store verb surface.

    ``root`` is a directory (one ``shard-NN.sqlite`` per backend) or
    ``":memory:"`` (tests/benches). The shard count is claimed into the
    meta shard's config on first open and pinned: reopening a store
    sharded at K with a different K is refused — the hash routing would
    silently strand every row. The same claim seeds the fleet-wide
    ``num_shards`` agent partition count, aligning agent shards with
    store shards so ``shard-<i>`` fences check atomically on backend i.
    """

    #: satellite 1 (shard-scoped resync): agents probe this to learn the
    #: store can scan a shard subset server-side instead of full-scanning
    store_num_shards: int = 0

    def __init__(self, root: str = ":memory:", shards: int = 4,
                 metrics=None, replicate: bool = True):
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.path = root
        self.num_shards = int(shards)
        self.store_num_shards = self.num_shards
        from ..obs.metrics import MetricsRegistry

        # ONE registry across every backend: Store's peer-aggregation
        # contract (counters SUM across _store_sources, epoch takes the
        # max) gives the sharded deployment one pane of glass for free
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        paths: list[str]
        if root == ":memory:":
            paths = [":memory:"] * self.num_shards
        else:
            os.makedirs(root, exist_ok=True)
            paths = [os.path.join(root, f"shard-{i:02d}.sqlite")
                     for i in range(self.num_shards)]
        self._shards: list[Store] = [
            Store(p, metrics=self.metrics, replicate=replicate)
            for p in paths]
        self._meta = self._shards[0]
        self._listener_lock = threading.Lock()
        if root != ":memory:":
            claimed = self._meta.claim_config(
                "store_num_shards", str(self.num_shards))
            if int(claimed) != self.num_shards:
                raise ValueError(
                    f"store at {root!r} was sharded at {claimed} backends; "
                    f"reopening at {self.num_shards} would strand rows "
                    "(crc32 routing) — use the original shard count")
        # align the fleet's work partitions with the store's shards: the
        # first writer wins, so a store opened before any agent pins
        # num_shards == K and every shard-<i> fence checks on backend i
        self._meta.claim_config("num_shards", str(self.num_shards))

    # -- routing helpers ---------------------------------------------------

    @property
    def backends(self) -> list[Store]:
        """The per-shard backends, index == shard index (backend 0 is
        also the meta shard). Replication/compaction tooling iterates
        this; everything else should go through the verbs."""
        return list(self._shards)

    def _shard_of(self, run_uuid: str) -> Store:
        return self._shards[shard_index(run_uuid, self.num_shards)]

    def _lease_home(self, name: str) -> Store:
        """``shard-<i>`` leases live IN backend i (atomic lifecycle
        fencing); presence and everything else live on the meta shard."""
        if name and name.startswith("shard-"):
            try:
                i = int(name.rsplit("-", 1)[1])
            except ValueError:
                return self._meta
            if 0 <= i < self.num_shards:
                return self._shards[i]
        return self._meta

    def _split_fence(self, target: Store, fence):
        """Re-home a fence for a write landing on ``target``. A fence
        whose lease lives on ``target`` passes through (checked inside
        the guarded transaction, exactly like the single store). A
        CROSS-shard fence is verified against the lease's home backend
        and then STRIPPED: the stale caller is still rejected
        (StaleLeaseError), but check and write are two transactions — a
        takeover landing exactly between them can let one guarded write
        through. Only non-lifecycle writes (quota/config/cluster) can
        hit this path; docs/RESILIENCE.md records the gap honestly."""
        if fence is None:
            return None
        name = fence[0]
        home = self._lease_home(name)
        if home is target:
            return fence
        with home._conn_ctx() as conn:
            home._check_fence(conn, fence)
        return None

    def _resolve_callable_fence(self, fence, run_uuid: Optional[str]):
        if callable(fence):
            return fence(run_uuid) if run_uuid else None
        return fence

    # -- feed tokens (composite vector) ------------------------------------

    def _pack(self, seqs: list) -> int:
        return pack_seqs(seqs)

    def _unpack(self, v: int) -> list[int]:
        return unpack_seqs(v, self.num_shards)

    def current_epoch(self) -> int:
        """SUM of the per-shard epochs: any single shard promoting
        changes it, so every epoch-qualified token minted before that
        failover is deterministically rejected (410)."""
        return sum(b.current_epoch() for b in self._shards)

    def current_seq(self) -> int:
        """Composite of the per-shard committed seqs. Each component is
        individually snapshot-consistent (an in-flight writer's rows land
        after it), so a bootstrap from this token is loss-free."""
        return self._pack([b.current_seq() for b in self._shards])

    def feed_token(self, seq: int) -> str:
        epoch = self.current_epoch()
        return f"{epoch}:{seq}" if epoch else str(seq)

    def parse_since(self, token) -> int:
        """Validate a composite feed token against the CURRENT epoch sum
        and return the composite seq (same contract as Store.parse_since:
        bare ints are internal callers and skip the epoch check)."""
        if isinstance(token, int):
            return token
        s = str(token)
        if ":" in s:
            e_str, _, seq_str = s.partition(":")
            epoch, seq = int(e_str), int(seq_str)
        else:
            epoch, seq = 0, int(s)
        current = self.current_epoch()
        if epoch != current:
            raise StaleEpochError(epoch, current)
        return seq

    def since_token(self, run: dict) -> str:
        """Resume token for a row delivered by a ``since`` listing: the
        composite cursor stamped onto the row at delivery (exact, loss-
        free). Rows from other paths fall back to a token that replays
        every OTHER shard from 0 — duplicate-heavy but never lossy."""
        tok = run.get("_since_token")
        if tok is not None:
            return tok
        vec = [0] * self.num_shards
        vec[shard_index(run["uuid"], self.num_shards)] = run["change_seq"]
        return self.feed_token(self._pack(vec))

    run_cursor = staticmethod(Store.run_cursor)

    # -- stitched changelog (the feed every consumer tails) ----------------

    def get_changelog(self, after_seq: int = 0,
                      limit: int = 500) -> list[dict]:
        """Merge the per-shard changelogs after the composite cursor into
        one totally-ordered page.

        Deterministic k-way merge by ``(created_at, shard_index)`` over
        the shard head rows; each emitted record advances exactly one
        component of the cursor vector, so the re-sequenced composite
        ``seq`` is strictly increasing along the page and across pages
        resumed from any returned seq. A truncated shard page (exactly
        ``limit`` rows came back) can never drain before the output page
        fills, so the merge never emits past a shard's unfetched rows.
        One shard's compacted tail raises :class:`CompactedLogError`
        whose floor is the composite with THAT component at the shard's
        floor — the 410 the tailer needs to re-bootstrap."""
        vec = self._unpack(after_seq)
        limit = int(limit)
        pages: list[list[dict]] = []
        for i, b in enumerate(self._shards):
            try:
                pages.append(b.get_changelog(vec[i], limit))
            except CompactedLogError as e:
                floor_vec = list(vec)
                floor_vec[i] = e.floor
                raise CompactedLogError(int(after_seq),
                                        self._pack(floor_vec)) from e
        heads = [0] * self.num_shards
        epoch = self.current_epoch()
        out: list[dict] = []
        cur = list(vec)
        while len(out) < limit:
            best = None
            for i, page in enumerate(pages):
                if heads[i] >= len(page):
                    continue
                rec = page[heads[i]]
                key = (rec["created_at"], i)
                if best is None or key < best[0]:
                    best = (key, i)
            if best is None:
                break
            i = best[1]
            rec = dict(pages[i][heads[i]])
            heads[i] += 1
            cur[i] = rec["seq"]
            rec["shard"] = i
            rec["shard_seq"] = rec["seq"]
            rec["shard_epoch"] = rec["epoch"]
            rec["seq"] = self._pack(cur)
            # consumers compare the record epoch to current_epoch()
            # (stream.py's epoch-flip detection): stitched records carry
            # the SUM, like every other sharded epoch surface
            rec["epoch"] = epoch
            out.append(rec)
        return out

    def changelog_span(self) -> dict:
        return {
            "seq": self._pack([b.changelog_span()["seq"]
                               for b in self._shards]),
            "epoch": self.current_epoch(),
        }

    def apply_changelog(self, rows: list[dict]) -> int:
        """Replay a STITCHED tail (a sharded standby's write path): demux
        each record back to its shard by the ``shard``/``shard_seq``
        markers the stitcher stamped, and replay per backend — each
        backend keeps its own idempotent applied watermark."""
        groups: dict[int, list[dict]] = {}
        for rec in rows:
            if "shard" not in rec:
                raise ValueError(
                    "apply_changelog on a ShardedStore needs stitched "
                    "records (shard/shard_seq markers); got a raw row — "
                    "replicate per backend via .backends instead")
            groups.setdefault(int(rec["shard"]), []).append({
                "seq": rec["shard_seq"],
                "epoch": rec.get("shard_epoch", rec["epoch"]),
                "op": rec["op"],
                "payload": rec["payload"],
                "created_at": rec["created_at"],
            })
        applied = 0
        for i in sorted(groups):
            applied += self._shards[i].apply_changelog(groups[i])
        return applied

    @property
    def _applied_seq(self) -> int:
        """Composite applied watermark (ReplicatedStandby reads this to
        seed its cursor on attach/restart)."""
        return self._pack([b._applied_seq for b in self._shards])

    def promote(self) -> int:
        """Promote every shard (epoch bump + lease wipe per backend);
        returns the new epoch SUM. Single-shard failover (one backend
        restored from its own snapshot/standby) bumps only that shard's
        epoch — the sum still changes, so every composite token dies."""
        for b in self._shards:
            b.promote()
        return self.current_epoch()

    def snapshot(self, dirpath: str) -> dict:
        """Per-shard snapshots under ``shard-NN/`` subdirs plus a
        combined manifest (composite seq, epoch sum)."""
        manifests = []
        for i, b in enumerate(self._shards):
            manifests.append(
                b.snapshot(os.path.join(dirpath, f"shard-{i:02d}")))
        return {
            "num_shards": self.num_shards,
            "shards": manifests,
            "seq": self._pack([m["seq"] for m in manifests]),
            "epoch": self.current_epoch(),
            "created_at": manifests[0]["created_at"],
        }

    # -- run fan-out verbs -------------------------------------------------

    def create_run(self, project: str, spec: Optional[dict] = None,
                   name: Optional[str] = None, kind: Optional[str] = None,
                   inputs: Optional[dict] = None, meta: Optional[dict] = None,
                   tags: Optional[list] = None, uuid: Optional[str] = None,
                   original_uuid: Optional[str] = None,
                   cloning_kind: Optional[str] = None,
                   pipeline_uuid: Optional[str] = None,
                   created_by: Optional[str] = None,
                   tenant: Optional[str] = None, fence=None) -> dict:
        return self.create_runs(project, [dict(
            spec=spec, name=name, kind=kind, inputs=inputs, meta=meta,
            tags=tags, uuid=uuid, original_uuid=original_uuid,
            cloning_kind=cloning_kind, pipeline_uuid=pipeline_uuid,
            created_by=created_by, tenant=tenant,
        )], fence=fence)[0]

    def create_runs(self, project: str, runs: list[dict],
                    fence=None) -> list[dict]:
        """Split the batch into per-shard transactions by each entry's
        (pre-assigned) uuid hash. Pipeline-parent inheritance
        (created_by/tenant) resolves HERE, through routed lookups — the
        parent may live on a different shard than its children, so the
        backend's own same-db lookup can't be trusted with it."""
        if callable(fence):
            puid = next((r.get("pipeline_uuid") for r in runs
                         if r.get("pipeline_uuid")), None)
            fence = fence(puid) if puid else None
        self._meta.create_project(project)
        parents: dict[str, Optional[dict]] = {}
        entries: list[dict] = []
        for r in runs:
            r = dict(r)
            r["uuid"] = r.get("uuid") or uuid_mod.uuid4().hex
            puid = r.get("pipeline_uuid")
            if puid and (r.get("created_by") is None
                         or r.get("tenant") is None):
                if puid not in parents:
                    parents[puid] = self.get_run(puid)
                parent = parents[puid]
                if parent:
                    if r.get("created_by") is None:
                        r["created_by"] = parent.get("created_by")
                    if r.get("tenant") is None:
                        r["tenant"] = parent.get("tenant")
            entries.append(r)
        groups: dict[int, list[dict]] = {}
        for r in entries:
            groups.setdefault(
                shard_index(r["uuid"], self.num_shards), []).append(r)
        by_uuid: dict[str, dict] = {}
        for i in sorted(groups):
            target = self._shards[i]
            out = target.create_runs(
                project, groups[i],
                fence=self._split_fence(target, fence))
            for row in out:
                by_uuid[row["uuid"]] = row
        return [by_uuid[r["uuid"]] for r in entries]

    def transition(self, run_uuid: str, status: str,
                   reason: Optional[str] = None,
                   message: Optional[str] = None, force: bool = False,
                   fence=None) -> tuple[Optional[dict], bool]:
        # single-edge fast path: route straight to the owning backend —
        # executor status callbacks fire this once per lifecycle edge
        # across the whole fleet, and the batch-grouping machinery is
        # pure overhead for one run
        target = self._shards[shard_index(run_uuid, self.num_shards)]
        return target.transition(
            run_uuid, status, reason=reason, message=message, force=force,
            fence=self._split_fence(
                target, self._resolve_callable_fence(fence, run_uuid)))

    def transition_many(self, transitions: list[tuple],
                        fence=None) -> list[tuple[Optional[dict], bool]]:
        """Per-shard sub-batches, one transaction each (PR 6 semantics:
        a fence rejection fails only its shard's sub-batch — here the
        split happens by STORE shard, and the error propagates to the
        caller exactly like the single store's). Entry order is preserved
        within each shard; results come back in input order."""
        groups: dict[tuple, list[tuple[int, tuple]]] = {}
        order: list[tuple] = []
        for idx, t in enumerate(transitions):
            si = shard_index(t[0], self.num_shards)
            f = self._resolve_callable_fence(fence, t[0])
            key = (si, f)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append((idx, t))
        results: list = [None] * len(transitions)
        for key in order:
            si, f = key
            target = self._shards[si]
            out = target.transition_many(
                [t for _, t in groups[key]],
                fence=self._split_fence(target, f))
            for (idx, _), r in zip(groups[key], out):
                results[idx] = r
        return results

    def get_runs(self, uuids: list[str]) -> list[dict]:
        groups: dict[int, list[str]] = {}
        for u in uuids:
            groups.setdefault(shard_index(u, self.num_shards), []).append(u)
        by_uuid: dict[str, dict] = {}
        for i, us in groups.items():
            for row in self._shards[i].get_runs(us):
                by_uuid[row["uuid"]] = row
        return [by_uuid[u] for u in uuids if u in by_uuid]

    def find_cached_run(self, project: str,
                        cache_key: str) -> Optional[dict]:
        for b in self._shards:
            hit = b.find_cached_run(project, cache_key)
            if hit is not None:
                return hit
        return None

    # -- merged listings ---------------------------------------------------

    def list_runs(self, project: Optional[str] = None,
                  status: Optional[str] = None,
                  pipeline_uuid: Optional[str] = None,
                  limit: int = 100, offset: int = 0,
                  statuses: Optional[list[str]] = None,
                  created_by: Optional[str] = None,
                  order: str = "desc", cursor: Optional[str] = None,
                  since: Optional[str] = None,
                  shards: Optional[list[int]] = None) -> list[dict]:
        """Single-store listing semantics over K backends.

        Keyset/offset mode merge-sorts per-shard pages by
        ``(created_at, uuid)`` — each shard applies the same cursor
        predicate, so the merged walk is the same total order the single
        store serves. ``since`` mode walks the shards' deltas in shard
        order, stamping each row's exact composite resume cursor
        (consumed via :meth:`since_token`): a truncated page resumes
        mid-shard, untouched shards replay from the caller's token —
        loss-free either way. ``shards`` scopes the scan to those
        backends only (satellite 1: an agent resyncing shard i reads
        backend i, not K backends x the whole table)."""
        filters = dict(project=project, status=status,
                       pipeline_uuid=pipeline_uuid, statuses=statuses,
                       created_by=created_by)
        targets = (list(enumerate(self._shards)) if shards is None else
                   [(i, self._shards[i]) for i in sorted(set(shards))
                    if 0 <= i < self.num_shards])
        if since is not None:
            vec = self._unpack(self.parse_since(since))
            want = int(limit) + int(offset)
            out: list[dict] = []
            for i, b in targets:
                if len(out) >= want:
                    break
                rows = b.list_runs(**filters, limit=want - len(out),
                                   since=vec[i])
                for r in rows:
                    vec[i] = r["change_seq"]
                    r["_since_token"] = self.feed_token(self._pack(vec))
                    out.append(r)
            return out[offset:offset + limit]
        if order not in ("desc", "asc"):
            raise ValueError(f"bad order {order!r}")
        per = int(limit) + int(offset)
        merged: list[dict] = []
        for _, b in targets:
            merged.extend(b.list_runs(**filters, limit=per, order=order,
                                      cursor=cursor))
        merged.sort(key=lambda r: (r["created_at"], r["uuid"]),
                    reverse=(order == "desc"))
        return merged[offset:offset + limit]

    def count_runs(self, project: Optional[str] = None,
                   status: Optional[str] = None,
                   pipeline_uuid: Optional[str] = None,
                   statuses: Optional[list[str]] = None,
                   created_by: Optional[str] = None) -> int:
        """Sum of the per-shard counts — each backend serves its count
        from the write-path row counters when the filters allow (the
        first-page COUNT(*) satellite), so a paged-listing bootstrap
        costs K dict lookups, not K table scans."""
        return sum(b.count_runs(project=project, status=status,
                                pipeline_uuid=pipeline_uuid,
                                statuses=statuses, created_by=created_by)
                   for b in self._shards)

    # -- leases ------------------------------------------------------------

    def acquire_lease(self, name: str, holder: str, *a: Any,
                      **kw: Any):
        return self._lease_home(name).acquire_lease(name, holder, *a, **kw)

    def renew_lease(self, name: str, holder: str, token: int) -> bool:
        return self._lease_home(name).renew_lease(name, holder, token)

    def renew_leases(self, renewals: list[tuple],
                     holder: str) -> list[bool]:
        groups: dict[int, list[tuple[int, tuple]]] = {}
        for idx, renewal in enumerate(renewals):
            home = self._lease_home(renewal[0])
            groups.setdefault(self._shards.index(home), []).append(
                (idx, renewal))
        results: list[bool] = [False] * len(renewals)
        for i, entries in groups.items():
            out = self._shards[i].renew_leases(
                [r for _, r in entries], holder)
            for (idx, _), ok in zip(entries, out):
                results[idx] = ok
        return results

    def release_lease(self, name: str, holder: str, token: int) -> bool:
        return self._lease_home(name).release_lease(name, holder, token)

    def get_lease(self, name: str) -> Optional[dict]:
        return self._lease_home(name).get_lease(name)

    def list_leases(self, prefix: Optional[str] = None) -> list[dict]:
        """Aggregate across backends (shard-<i> rows live on backend i,
        presence rows on meta — disjoint by construction)."""
        rows: list[dict] = []
        for b in self._shards:
            rows.extend(b.list_leases(prefix))
        rows.sort(key=lambda r: r["name"])
        return rows

    # -- serve traffic -----------------------------------------------------

    def serve_traffic(self, uuid: Optional[str] = None) -> dict:
        if uuid is not None:
            return self._shard_of(uuid).serve_traffic(uuid)
        totals: dict = {}
        for b in self._shards:
            for k, v in b.serve_traffic().items():
                if isinstance(v, (int, float)):
                    if k.endswith("utilization"):
                        totals[k] = max(totals.get(k, 0.0), v)
                    else:
                        totals[k] = totals.get(k, 0) + v
                else:
                    totals.setdefault(k, v)
        return totals

    @property
    def serve_fresh_s(self) -> float:
        return self._meta.serve_fresh_s

    @serve_fresh_s.setter
    def serve_fresh_s(self, value: float) -> None:
        for b in self._shards:
            b.serve_fresh_s = value

    # -- cross-backend state -----------------------------------------------

    def cluster_load(self) -> dict[str, int]:
        totals: dict[str, int] = {}
        for b in self._shards:
            for name, n in b.cluster_load().items():
                totals[name] = totals.get(name, 0) + n
        return totals

    @property
    def stats(self) -> dict:
        """Aggregated backend counters (sums). A snapshot view — writers
        go through verbs, never this dict."""
        totals: dict = {}
        for b in self._shards:
            for k, v in b.stats.items():
                totals[k] = totals.get(k, 0) + v
        return totals

    def add_transition_listener(self, fn) -> None:
        with self._listener_lock:
            for b in self._shards:
                b.add_transition_listener(fn)

    def set_read_only(self, flag: bool) -> None:
        for b in self._shards:
            b.set_read_only(flag)

    @property
    def read_only(self) -> bool:
        return any(b.read_only for b in self._shards)

    @property
    def degraded(self) -> Optional[str]:
        for b in self._shards:
            if b.degraded is not None:
                return b.degraded
        return None

    def probe_recovery(self) -> bool:
        return all(b.probe_recovery() for b in self._shards)

    def chaos_disk_full(self, n: int = 1) -> None:
        for b in self._shards:
            b.chaos_disk_full(n)


#: run-scoped verbs: routed to the owning shard, fence re-homed
for _name in (
    "get_run", "get_statuses", "update_run", "merge_outputs", "heartbeat",
    "annotate_status", "delete_run", "record_launch_intent",
    "mark_launched", "adopt_launch", "get_launch_intent", "add_lineage",
    "get_lineage", "serve_replica_drain", "serve_progress", "place_run",
    # sweep trial intents (ISSUE 19): first arg is the sweep (pipeline)
    # uuid, so intents land on the SAME shard as the pipeline row and the
    # children created under its fence
    "record_trial_intents", "mark_trials_created", "list_trial_intents",
):
    setattr(ShardedStore, _name, _run_scoped(_name))

#: control-plane verbs: routed to the meta shard
for _name in (
    "create_project", "get_project", "list_projects",
    "create_token", "resolve_token", "list_tokens", "revoke_token",
    "has_tokens",
    "claim_config", "get_config", "set_config",
    "set_quota", "get_quota", "list_quotas", "delete_quota",
    "get_quota_map",
    "register_cluster", "get_cluster", "list_clusters", "delete_cluster",
    "get_cluster_map",
    "count_serve_retries",
    # SLO alerts (ISSUE 20): fleet-scoped control-plane state like quotas
    # — one alert table, regardless of how the run space is sharded (the
    # evaluator's cross-shard fence is verified on its lease home by
    # _split_fence, then stripped, exactly like a quota write)
    "upsert_alert", "resolve_alert", "get_alert", "list_alerts",
):
    setattr(ShardedStore, _name, _meta_scoped(_name))
del _name


__all__ = ["SHARD_SEQ_BITS", "ShardedStore", "pack_seqs", "unpack_seqs"]
