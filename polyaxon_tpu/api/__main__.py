from .server import main

main()
