"""SQLite-backed persistence for projects/runs/statuses (the API service
DB — upstream used Django+Postgres, SURVEY.md §2 "API service"; SQLite is
the local/agent deployment default and is WAL-mode safe across the API and
scheduler threads)."""

from __future__ import annotations

import datetime
import json
import sqlite3
import threading
import uuid as uuid_mod
from typing import Any, Optional

from ..schemas.statuses import DONE_STATUSES, V1StatusCondition, V1Statuses, can_transition, is_done

_SCHEMA = """
CREATE TABLE IF NOT EXISTS projects (
    name TEXT PRIMARY KEY,
    description TEXT,
    created_at TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS runs (
    uuid TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    name TEXT,
    kind TEXT,
    status TEXT NOT NULL,
    spec TEXT,
    compiled TEXT,
    inputs TEXT,
    outputs TEXT,
    meta TEXT,
    tags TEXT,
    original_uuid TEXT,
    cloning_kind TEXT,
    pipeline_uuid TEXT,
    created_by TEXT,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL,
    started_at TEXT,
    finished_at TEXT,
    heartbeat_at TEXT
);
CREATE INDEX IF NOT EXISTS idx_runs_project ON runs (project, created_at);
CREATE INDEX IF NOT EXISTS idx_runs_status ON runs (status);
CREATE INDEX IF NOT EXISTS idx_runs_pipeline ON runs (pipeline_uuid);
CREATE TABLE IF NOT EXISTS status_conditions (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_uuid TEXT NOT NULL,
    condition TEXT NOT NULL,
    created_at TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_conditions_run ON status_conditions (run_uuid);
CREATE TABLE IF NOT EXISTS lineage (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_uuid TEXT NOT NULL,
    name TEXT,
    artifact TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_lineage_run ON lineage (run_uuid);
CREATE TABLE IF NOT EXISTS tokens (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    token_hash TEXT NOT NULL UNIQUE,
    project TEXT,
    label TEXT,
    created_at TEXT NOT NULL,
    revoked INTEGER NOT NULL DEFAULT 0
);
"""


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


class Store:
    """Thread-safe SQLite store. One connection per thread (sqlite3
    check_same_thread), WAL so readers never block the writer."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._local = threading.local()
        # serializes status transitions (read-check-insert-update must be
        # atomic across the agent/executor/API threads)
        self._transition_lock = threading.Lock()
        self._transition_listeners: list = []
        self._memory_conn: Optional[sqlite3.Connection] = None
        if path == ":memory:":
            # a single shared connection (serialized by a lock)
            self._memory_conn = sqlite3.connect(":memory:", check_same_thread=False)
            self._memory_lock = threading.Lock()
        with self._conn_ctx() as conn:
            conn.executescript(_SCHEMA)
            # additive migration for pre-r5 databases (CREATE TABLE IF NOT
            # EXISTS won't grow an existing table)
            cols = {r[1] for r in conn.execute("PRAGMA table_info(runs)")}
            if "created_by" not in cols:
                conn.execute("ALTER TABLE runs ADD COLUMN created_by TEXT")
            if "heartbeat_at" not in cols:
                conn.execute("ALTER TABLE runs ADD COLUMN heartbeat_at TEXT")

    # -- connection plumbing ----------------------------------------------

    def _conn_ctx(self):
        store = self

        class _Ctx:
            def __enter__(self):
                if store._memory_conn is not None:
                    store._memory_lock.acquire()
                    return store._memory_conn
                conn = getattr(store._local, "conn", None)
                if conn is None:
                    conn = sqlite3.connect(store.path, timeout=30)
                    conn.execute("PRAGMA journal_mode=WAL")
                    conn.execute("PRAGMA synchronous=NORMAL")
                    store._local.conn = conn
                return conn

            def __exit__(self, et, ev, tb):
                if store._memory_conn is not None:
                    if et is None:
                        store._memory_conn.commit()
                    store._memory_lock.release()
                else:
                    if et is None:
                        store._local.conn.commit()

        return _Ctx()

    # -- projects ----------------------------------------------------------

    def create_project(self, name: str, description: Optional[str] = None) -> dict:
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT OR IGNORE INTO projects (name, description, created_at) VALUES (?,?,?)",
                (name, description, _now()),
            )
        return self.get_project(name)

    def get_project(self, name: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT name, description, created_at FROM projects WHERE name=?", (name,)
            ).fetchone()
        if not row:
            return None
        return {"name": row[0], "description": row[1], "created_at": row[2]}

    def list_projects(self) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT name, description, created_at FROM projects ORDER BY name"
            ).fetchall()
        return [{"name": r[0], "description": r[1], "created_at": r[2]} for r in rows]

    # -- tokens (RBAC-lite, SURVEY.md:104) ----------------------------------

    @staticmethod
    def _token_hash(raw: str) -> str:
        import hashlib

        return hashlib.sha256(raw.encode()).hexdigest()

    def create_token(self, project: Optional[str] = None,
                     label: Optional[str] = None) -> dict:
        """Mint an access token. ``project=None`` = admin (all projects);
        otherwise scoped to that one project. Only the sha256 lands in the
        DB — the raw token is returned once and never recoverable."""
        import secrets

        raw = secrets.token_hex(24)
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "INSERT INTO tokens (token_hash, project, label, created_at) "
                "VALUES (?,?,?,?)",
                (self._token_hash(raw), project, label, _now()),
            )
            tid = cur.lastrowid
        return {"id": tid, "token": raw, "project": project, "label": label}

    def resolve_token(self, raw: str) -> Optional[dict]:
        """{'id', 'project', 'label'} for a live token (project None =
        admin), or None for unknown/revoked."""
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT id, project, label FROM tokens "
                "WHERE token_hash=? AND revoked=0",
                (self._token_hash(raw),),
            ).fetchone()
        return ({"id": row[0], "project": row[1], "label": row[2]}
                if row else None)

    def list_tokens(self) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT id, project, label, created_at, revoked FROM tokens "
                "ORDER BY id"
            ).fetchall()
        return [{"id": r[0], "project": r[1], "label": r[2],
                 "created_at": r[3], "revoked": bool(r[4])} for r in rows]

    def revoke_token(self, token_id: int) -> bool:
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "UPDATE tokens SET revoked=1 WHERE id=?", (token_id,))
            return cur.rowcount > 0

    def has_tokens(self) -> bool:
        """Any token row, revoked or not: once a server has ever minted a
        token, auth stays engaged across restarts — revoking the last token
        must lock the server down, not silently reopen it.

        Break-glass recovery (ADVICE r4): the lockdown has no *network*
        escape hatch by design, but an operator with shell access to the
        server host can always recover — start the server with
        ``--auth-token <secret>`` (the static admin token bypasses the
        store) and mint a fresh scoped token via ``POST /api/v1/tokens``,
        or delete rows from the ``tokens`` table in the store's sqlite db.
        Documented in README "Auth"."""
        with self._conn_ctx() as conn:
            return conn.execute(
                "SELECT 1 FROM tokens LIMIT 1").fetchone() is not None

    # -- runs --------------------------------------------------------------

    _RUN_COLS = (
        "uuid", "project", "name", "kind", "status", "spec", "compiled",
        "inputs", "outputs", "meta", "tags", "original_uuid", "cloning_kind",
        "pipeline_uuid", "created_by", "created_at", "updated_at",
        "started_at", "finished_at", "heartbeat_at",
    )
    _JSON_COLS = {"spec", "compiled", "inputs", "outputs", "meta", "tags"}

    def _row_to_run(self, row) -> dict:
        d = dict(zip(self._RUN_COLS, row))
        for c in self._JSON_COLS:
            d[c] = json.loads(d[c]) if d[c] else None
        return d

    @staticmethod
    def _params_to_inputs(spec: dict) -> Optional[dict]:
        """A run's queryable inputs default to its bound param values
        (upstream stored resolved params on the run row; compare/sort
        read them). Ref params carry an unresolved context expression as
        their value and context_only params aren't inputs — skip both."""
        params = spec.get("params") or {}
        out = {}
        for k, v in params.items():
            if isinstance(v, dict):
                if v.get("ref") or v.get("context_only") or v.get("contextOnly"):
                    continue
                out[k] = v.get("value")
            else:
                out[k] = v
        return out or None

    def create_run(
        self,
        project: str,
        spec: Optional[dict] = None,
        name: Optional[str] = None,
        kind: Optional[str] = None,
        inputs: Optional[dict] = None,
        meta: Optional[dict] = None,
        tags: Optional[list] = None,
        uuid: Optional[str] = None,
        original_uuid: Optional[str] = None,
        cloning_kind: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        created_by: Optional[str] = None,
    ) -> dict:
        self.create_project(project)
        if inputs is None and spec:
            # one place for every creation path (CLI, client, server, DAG
            # and schedule children, tuner trials pass explicit inputs)
            inputs = self._params_to_inputs(spec)
        if created_by is None and pipeline_uuid:
            # pipeline children (DAG stages, sweep trials, schedule runs)
            # inherit their parent's owner — ownership filtering must not
            # split a user's pipeline from its stages (review r5)
            parent = self.get_run(pipeline_uuid)
            if parent:
                created_by = parent.get("created_by")
        run_uuid = uuid or uuid_mod.uuid4().hex
        now = _now()
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT INTO runs (uuid, project, name, kind, status, spec, inputs, meta, tags,"
                " original_uuid, cloning_kind, pipeline_uuid, created_by, created_at, updated_at)"
                " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    run_uuid, project, name, kind, V1Statuses.CREATED.value,
                    json.dumps(spec) if spec else None,
                    json.dumps(inputs) if inputs else None,
                    json.dumps(meta) if meta else None,
                    json.dumps(tags) if tags else None,
                    original_uuid, cloning_kind, pipeline_uuid, created_by,
                    now, now,
                ),
            )
            conn.execute(
                "INSERT INTO status_conditions (run_uuid, condition, created_at) VALUES (?,?,?)",
                (run_uuid,
                 json.dumps(V1StatusCondition.get_condition(V1Statuses.CREATED).to_dict()),
                 now),
            )
        # creation flows through the same feed as transitions so a
        # subscribed agent learns about new runs without scanning
        for listener in self._transition_listeners:
            try:
                listener(run_uuid, V1Statuses.CREATED.value)
            except Exception:
                import traceback

                traceback.print_exc()
        return self.get_run(run_uuid)

    def get_run(self, uuid: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._RUN_COLS)} FROM runs WHERE uuid=?", (uuid,)
            ).fetchone()
        return self._row_to_run(row) if row else None

    def list_runs(
        self,
        project: Optional[str] = None,
        status: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        limit: int = 100,
        offset: int = 0,
        statuses: Optional[list[str]] = None,
        created_by: Optional[str] = None,
    ) -> list[dict]:
        q = f"SELECT {','.join(self._RUN_COLS)} FROM runs WHERE 1=1"
        args: list = []
        if project:
            q += " AND project=?"
            args.append(project)
        if created_by:
            q += " AND created_by=?"
            args.append(created_by)
        if status:
            q += " AND status=?"
            args.append(status)
        if statuses:
            q += f" AND status IN ({','.join('?' * len(statuses))})"
            args.extend(statuses)
        if pipeline_uuid:
            q += " AND pipeline_uuid=?"
            args.append(pipeline_uuid)
        q += " ORDER BY created_at DESC LIMIT ? OFFSET ?"
        args += [limit, offset]
        with self._conn_ctx() as conn:
            rows = conn.execute(q, args).fetchall()
        return [self._row_to_run(r) for r in rows]

    def update_run(self, uuid: str, **fields: Any) -> Optional[dict]:
        sets, args = [], []
        for k, v in fields.items():
            if k not in self._RUN_COLS or k == "uuid":
                raise ValueError(f"bad run field {k!r}")
            if k in self._JSON_COLS and v is not None and not isinstance(v, str):
                v = json.dumps(v)
            sets.append(f"{k}=?")
            args.append(v)
        sets.append("updated_at=?")
        args.append(_now())
        args.append(uuid)
        with self._conn_ctx() as conn:
            conn.execute(f"UPDATE runs SET {','.join(sets)} WHERE uuid=?", args)
        return self.get_run(uuid)

    def merge_outputs(self, uuid: str, outputs: dict) -> Optional[dict]:
        # serialize the read-modify-write: concurrent writers (API
        # post_outputs, agent _collect_outputs, tuner merge) must not drop keys
        with self._transition_lock:
            run = self.get_run(uuid)
            if run is None:
                return None
            merged = dict(run.get("outputs") or {})
            merged.update(outputs)
            return self.update_run(uuid, outputs=merged)

    def heartbeat(self, uuid: str) -> bool:
        """Renew a run's liveness lease (zombie-reaper input). Cheap direct
        UPDATE — no listeners fire, no updated_at churn."""
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "UPDATE runs SET heartbeat_at=? WHERE uuid=?", (_now(), uuid))
        return cur.rowcount > 0

    def delete_run(self, uuid: str) -> bool:
        with self._conn_ctx() as conn:
            cur = conn.execute("DELETE FROM runs WHERE uuid=?", (uuid,))
            conn.execute("DELETE FROM status_conditions WHERE run_uuid=?", (uuid,))
            conn.execute("DELETE FROM lineage WHERE run_uuid=?", (uuid,))
        return cur.rowcount > 0

    # -- statuses ----------------------------------------------------------

    def transition(
        self, uuid: str, status: str, reason: Optional[str] = None,
        message: Optional[str] = None, force: bool = False,
    ) -> tuple[Optional[dict], bool]:
        """Apply a status transition if legal. Returns (run, changed).
        Atomic: the check + condition insert + status update hold one lock so
        concurrent writers (agent vs executor threads) cannot interleave —
        e.g. a late 'failed' from a killed process must not overwrite
        'stopped'."""
        with self._transition_lock:
            run = self.get_run(uuid)
            if run is None:
                return None, False
            src = V1Statuses(run["status"])
            dst = V1Statuses(status)
            if (not force or src in DONE_STATUSES) and not can_transition(src, dst):
                return run, False
            cond = V1StatusCondition.get_condition(dst, reason=reason, message=message)
            now = _now()
            fields: dict[str, Any] = {"status": dst.value}
            if dst == V1Statuses.RUNNING and not run.get("started_at"):
                fields["started_at"] = now
            if is_done(dst):
                fields["finished_at"] = now
            with self._conn_ctx() as conn:
                conn.execute(
                    "INSERT INTO status_conditions (run_uuid, condition, created_at) VALUES (?,?,?)",
                    (uuid, json.dumps(cond.to_dict()), now),
                )
            result = self.update_run(uuid, **fields), True
        # observers run OUTSIDE the lock (they may read the store) and only
        # for transitions that actually happened — hooks keyed off rejected
        # late reports (a killed process's 'failed' after 'stopped') never
        # fire with the wrong status
        for listener in self._transition_listeners:
            try:
                listener(uuid, dst.value)
            except Exception:
                import traceback

                traceback.print_exc()
        return result

    def add_transition_listener(self, fn) -> None:
        """Register ``fn(uuid, new_status)`` called after every applied
        transition (any writer: agent, executor callbacks, API clients)."""
        self._transition_listeners.append(fn)

    def find_cached_run(self, project: str, cache_key: str) -> Optional[dict]:
        """Most recent succeeded run in ``project`` whose meta.cache_key
        matches — SQL-side so the lookup is one row, not a page scan."""
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._RUN_COLS)} FROM runs "
                "WHERE project=? AND status='succeeded' "
                "AND json_extract(meta, '$.cache_key')=? "
                "ORDER BY created_at DESC LIMIT 1",
                (project, cache_key),
            ).fetchone()
        return self._row_to_run(row) if row else None

    def get_statuses(self, uuid: str) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT condition FROM status_conditions WHERE run_uuid=? ORDER BY id",
                (uuid,),
            ).fetchall()
        return [json.loads(r[0]) for r in rows]

    # -- lineage -----------------------------------------------------------

    def add_lineage(self, uuid: str, artifact: dict) -> None:
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT INTO lineage (run_uuid, name, artifact) VALUES (?,?,?)",
                (uuid, artifact.get("name"), json.dumps(artifact)),
            )

    def get_lineage(self, uuid: str) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT artifact FROM lineage WHERE run_uuid=? ORDER BY id", (uuid,)
            ).fetchall()
        return [json.loads(r[0]) for r in rows]
