"""SQLite-backed persistence for projects/runs/statuses (the API service
DB — upstream used Django+Postgres, SURVEY.md §2 "API service"; SQLite is
the local/agent deployment default and is WAL-mode safe across the API and
scheduler threads)."""

from __future__ import annotations

import datetime
import json
import sqlite3
import threading
import time
import uuid as uuid_mod
import zlib
from typing import Any, Optional

from ..resilience.heartbeat import age_seconds
from ..schemas.statuses import DONE_STATUSES, V1StatusCondition, V1Statuses, can_transition, is_done

_SCHEMA = """
CREATE TABLE IF NOT EXISTS projects (
    name TEXT PRIMARY KEY,
    description TEXT,
    created_at TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS runs (
    uuid TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    name TEXT,
    kind TEXT,
    status TEXT NOT NULL,
    spec TEXT,
    compiled TEXT,
    inputs TEXT,
    outputs TEXT,
    meta TEXT,
    tags TEXT,
    original_uuid TEXT,
    cloning_kind TEXT,
    pipeline_uuid TEXT,
    created_by TEXT,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL,
    started_at TEXT,
    finished_at TEXT,
    heartbeat_at TEXT,
    change_seq INTEGER
);
-- monotone change counter: bumped INSIDE every write transaction (the
-- UPDATE takes SQLite's single-writer lock, so seq order == commit
-- order), which is what makes ?since= incremental fetches loss-free —
-- wall-clock timestamps can be stamped before a competing commit lands
CREATE TABLE IF NOT EXISTS counters (
    k TEXT PRIMARY KEY,
    v INTEGER NOT NULL
);
INSERT OR IGNORE INTO counters (k, v) VALUES ('change_seq', 0);
CREATE INDEX IF NOT EXISTS idx_runs_project ON runs (project, created_at);
CREATE INDEX IF NOT EXISTS idx_runs_status ON runs (status);
-- queue pops: the agent lists one status ordered by created_at (FIFO);
-- without the composite index SQLite picks idx_runs_status then sorts
CREATE INDEX IF NOT EXISTS idx_runs_status_created ON runs (status, created_at);
-- (idx_runs_change_seq is created post-migration in __init__: on a
-- pre-r7 db the column does not exist yet when this script runs)
CREATE INDEX IF NOT EXISTS idx_runs_pipeline ON runs (pipeline_uuid);
CREATE TABLE IF NOT EXISTS status_conditions (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_uuid TEXT NOT NULL,
    condition TEXT NOT NULL,
    created_at TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_conditions_run ON status_conditions (run_uuid);
CREATE TABLE IF NOT EXISTS lineage (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_uuid TEXT NOT NULL,
    name TEXT,
    artifact TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_lineage_run ON lineage (run_uuid);
CREATE TABLE IF NOT EXISTS tokens (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    token_hash TEXT NOT NULL UNIQUE,
    project TEXT,
    label TEXT,
    created_at TEXT NOT NULL,
    revoked INTEGER NOT NULL DEFAULT 0
);
-- control-plane crash safety (docs/RESILIENCE.md "Control-plane crash
-- matrix"): one row per named lease (the scheduler holds "scheduler").
-- ``token`` is the fencing token — monotonic across acquisitions AND
-- releases (the counter lives in ``counters`` under lease_token:<name>,
-- so a delete+reacquire can never reissue an old token). Agent-side
-- writes carry (name, token) and are rejected when the row's token
-- differs: a stale agent that wakes from a GC pause can observe but
-- not mutate.
CREATE TABLE IF NOT EXISTS agent_leases (
    name TEXT PRIMARY KEY,
    holder TEXT NOT NULL,
    token INTEGER NOT NULL,
    ttl REAL NOT NULL,
    acquired_at TEXT NOT NULL,
    renewed_at TEXT NOT NULL
);
-- write-ahead launch intents: the agent records (lease, token, attempt)
-- BEFORE asking the cluster for pods, so a restarted agent can tell
-- "intent recorded, pod never created" (safe to relaunch) from
-- "pods launched, row stale" (adopt — never a duplicate pod set).
CREATE TABLE IF NOT EXISTS launch_intents (
    run_uuid TEXT PRIMARY KEY,
    lease_name TEXT,
    lease_holder TEXT,
    token INTEGER,
    attempt INTEGER NOT NULL,
    state TEXT NOT NULL,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL
);
-- first-writer-wins control-plane settings the whole fleet must agree
-- on (num_shards: two agents hashing the run space with different K
-- would BOTH own some runs under valid fences — duplicate launches the
-- per-shard fencing cannot catch).
CREATE TABLE IF NOT EXISTS control_config (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
"""


SHARD_PREFIX = "shard-"
AGENT_PREFIX = "agent-"  # presence leases: one per live agent, self-named


def shard_index(run_uuid: str, num_shards: int) -> int:
    """Stable shard assignment for a run: crc32 of the uuid bytes mod K.

    Stability is load-bearing — every agent (and every incarnation of an
    agent, across processes and restarts) must map a uuid to the SAME
    shard, because the shard name keys both the lease that authorizes
    writes to the run and which agent's wait queue it lives in."""
    return zlib.crc32(run_uuid.encode("utf-8")) % max(int(num_shards), 1)


def shard_lease_names(num_shards: int) -> list[str]:
    """The lease names of a K-shard control plane: shard-0 .. shard-K-1."""
    return [f"{SHARD_PREFIX}{i}" for i in range(max(int(num_shards), 1))]


def shard_ownership(rows: list[dict]) -> tuple[list[dict], dict]:
    """Split a ``list_leases()`` result into the work-partition view
    served by ``GET /api/v1/stats`` and ``polyaxon status``: (work lease
    rows, ``{holder: [lease names]}`` for the live owners). Presence rows
    (``agent-*``) are fleet membership, not work — excluded; expired rows
    appear in the list (orphaned, awaiting adoption) but own nothing."""
    shards = [r for r in rows if not r["name"].startswith(AGENT_PREFIX)]
    owners: dict = {}
    for r in shards:
        if not r["expired"]:
            owners.setdefault(r["holder"], []).append(r["name"])
    return shards, owners


class StaleLeaseError(RuntimeError):
    """A fenced write carried a token older than the current lease — the
    writer lost its lease (TTL takeover, double-start, explicit release)
    and must stop mutating. The API surfaces this as HTTP 409."""

    def __init__(self, name: str, token: Optional[int],
                 current: Optional[int]):
        self.lease_name = name
        self.token = token
        self.current = current
        super().__init__(
            f"stale lease token {token} for lease {name!r} "
            f"(current: {current})")


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


class Store:
    """Thread-safe SQLite store. One connection per thread (sqlite3
    check_same_thread), WAL so readers never block the writer."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._local = threading.local()
        # serializes status transitions (read-check-insert-update must be
        # atomic across the agent/executor/API threads)
        self._transition_lock = threading.Lock()
        self._transition_listeners: list = []
        # cheap observability for scheduling-complexity tests and perf
        # triage: transactions opened + run rows deserialized. A dirty
        # scheduling pass must stay O(dirty) on both (tests/test_runtime_
        # agent.py asserts it), so the counters are part of the contract.
        self.stats = {"transactions": 0, "runs_deserialized": 0,
                      "fence_rejections": 0, "launch_intents": 0}
        # observability (ISSUE 5): the store is the hub every component
        # already shares, so its registry is the process's one pane of
        # glass — the agent/reaper/reconciler register their series here
        # and `GET /metrics` renders it. Counters export the existing
        # ``stats`` dict via callbacks (no double bookkeeping).
        from ..obs.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        for stat, help_txt in (
            ("transactions", "Store transactions opened"),
            ("runs_deserialized", "Run rows deserialized from the store"),
            ("fence_rejections",
             "Fenced writes rejected for a stale lease token"),
            ("launch_intents", "Write-ahead launch intents recorded"),
        ):
            self.metrics.counter(
                f"polyaxon_store_{stat}_total", help_txt,
                value_fn=(lambda s=stat: self.stats[s]))
        self._h_write = self.metrics.histogram(
            "polyaxon_store_write_seconds",
            "Latency of lifecycle write transactions "
            "(transition batches, run creation)")
        self._h_sched = self.metrics.histogram(
            "polyaxon_schedule_latency_seconds",
            "Run creation to first running transition "
            "(the sched_bench time-to-running metric)")
        self._memory_conn: Optional[sqlite3.Connection] = None
        if path == ":memory:":
            # a single shared connection (serialized by a lock)
            self._memory_conn = sqlite3.connect(":memory:", check_same_thread=False)
            self._memory_conn.execute("PRAGMA busy_timeout=10000")
            self._memory_lock = threading.Lock()
        with self._conn_ctx() as conn:
            conn.executescript(_SCHEMA)
            # additive migration for pre-r5 databases (CREATE TABLE IF NOT
            # EXISTS won't grow an existing table)
            cols = {r[1] for r in conn.execute("PRAGMA table_info(runs)")}
            if "created_by" not in cols:
                conn.execute("ALTER TABLE runs ADD COLUMN created_by TEXT")
            if "heartbeat_at" not in cols:
                conn.execute("ALTER TABLE runs ADD COLUMN heartbeat_at TEXT")
            if "change_seq" not in cols:
                # pre-r7: backfill in rowid (≈ insertion) order and point
                # the counter past the backfill
                conn.execute("ALTER TABLE runs ADD COLUMN change_seq INTEGER")
                conn.execute("UPDATE runs SET change_seq=rowid")
                conn.execute(
                    "UPDATE counters SET v=COALESCE("
                    "(SELECT MAX(change_seq) FROM runs), 0) "
                    "WHERE k='change_seq'")
            conn.execute("CREATE INDEX IF NOT EXISTS idx_runs_change_seq "
                         "ON runs (change_seq)")

    # -- connection plumbing ----------------------------------------------

    def _conn_ctx(self):
        store = self

        class _Ctx:
            def __enter__(self):
                store.stats["transactions"] += 1
                if store._memory_conn is not None:
                    store._memory_lock.acquire()
                    return store._memory_conn
                conn = getattr(store._local, "conn", None)
                if conn is None:
                    conn = sqlite3.connect(store.path, timeout=30)
                    conn.execute("PRAGMA journal_mode=WAL")
                    conn.execute("PRAGMA synchronous=NORMAL")
                    # don't fail instantly on a writer collision across
                    # processes (WAL allows one writer): wait it out
                    conn.execute("PRAGMA busy_timeout=10000")
                    store._local.conn = conn
                return conn

            def __exit__(self, et, ev, tb):
                # rollback on error, ALWAYS: python sqlite3 leaves the
                # implicit transaction open otherwise — a half-applied
                # write would hold the writer lock and get silently flushed
                # by the next unrelated commit on this connection
                if store._memory_conn is not None:
                    try:
                        if et is None:
                            store._memory_conn.commit()
                        else:
                            store._memory_conn.rollback()
                    finally:
                        store._memory_lock.release()
                else:
                    if et is None:
                        store._local.conn.commit()
                    else:
                        store._local.conn.rollback()

        return _Ctx()

    # -- projects ----------------------------------------------------------

    def create_project(self, name: str, description: Optional[str] = None) -> dict:
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT OR IGNORE INTO projects (name, description, created_at) VALUES (?,?,?)",
                (name, description, _now()),
            )
        return self.get_project(name)

    def get_project(self, name: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT name, description, created_at FROM projects WHERE name=?", (name,)
            ).fetchone()
        if not row:
            return None
        return {"name": row[0], "description": row[1], "created_at": row[2]}

    def list_projects(self) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT name, description, created_at FROM projects ORDER BY name"
            ).fetchall()
        return [{"name": r[0], "description": r[1], "created_at": r[2]} for r in rows]

    # -- tokens (RBAC-lite, SURVEY.md:104) ----------------------------------

    @staticmethod
    def _token_hash(raw: str) -> str:
        import hashlib

        return hashlib.sha256(raw.encode()).hexdigest()

    def create_token(self, project: Optional[str] = None,
                     label: Optional[str] = None) -> dict:
        """Mint an access token. ``project=None`` = admin (all projects);
        otherwise scoped to that one project. Only the sha256 lands in the
        DB — the raw token is returned once and never recoverable."""
        import secrets

        raw = secrets.token_hex(24)
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "INSERT INTO tokens (token_hash, project, label, created_at) "
                "VALUES (?,?,?,?)",
                (self._token_hash(raw), project, label, _now()),
            )
            tid = cur.lastrowid
        return {"id": tid, "token": raw, "project": project, "label": label}

    def resolve_token(self, raw: str) -> Optional[dict]:
        """{'id', 'project', 'label'} for a live token (project None =
        admin), or None for unknown/revoked."""
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT id, project, label FROM tokens "
                "WHERE token_hash=? AND revoked=0",
                (self._token_hash(raw),),
            ).fetchone()
        return ({"id": row[0], "project": row[1], "label": row[2]}
                if row else None)

    def list_tokens(self) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT id, project, label, created_at, revoked FROM tokens "
                "ORDER BY id"
            ).fetchall()
        return [{"id": r[0], "project": r[1], "label": r[2],
                 "created_at": r[3], "revoked": bool(r[4])} for r in rows]

    def revoke_token(self, token_id: int) -> bool:
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "UPDATE tokens SET revoked=1 WHERE id=?", (token_id,))
            return cur.rowcount > 0

    def has_tokens(self) -> bool:
        """Any token row, revoked or not: once a server has ever minted a
        token, auth stays engaged across restarts — revoking the last token
        must lock the server down, not silently reopen it.

        Break-glass recovery (ADVICE r4): the lockdown has no *network*
        escape hatch by design, but an operator with shell access to the
        server host can always recover — start the server with
        ``--auth-token <secret>`` (the static admin token bypasses the
        store) and mint a fresh scoped token via ``POST /api/v1/tokens``,
        or delete rows from the ``tokens`` table in the store's sqlite db.
        Documented in README "Auth"."""
        with self._conn_ctx() as conn:
            return conn.execute(
                "SELECT 1 FROM tokens LIMIT 1").fetchone() is not None

    # -- agent leases + fencing (control-plane crash safety) ---------------

    _LEASE_COLS = ("name", "holder", "token", "ttl", "acquired_at",
                   "renewed_at")

    @staticmethod
    def _lease_age(renewed_at: str) -> float:
        t = datetime.datetime.fromisoformat(renewed_at)
        if t.tzinfo is None:
            t = t.replace(tzinfo=datetime.timezone.utc)
        return (datetime.datetime.now(datetime.timezone.utc)
                - t).total_seconds()

    def _lease_row(self, conn, name: str) -> Optional[dict]:
        row = conn.execute(
            f"SELECT {','.join(self._LEASE_COLS)} FROM agent_leases "
            "WHERE name=?", (name,)).fetchone()
        return dict(zip(self._LEASE_COLS, row)) if row else None

    def acquire_lease(self, name: str, holder: str,
                      ttl: float = 30.0) -> Optional[dict]:
        """Take the named lease if it is free, expired (no renewal within
        its TTL), or already ours. Every successful acquisition bumps the
        monotonic fencing token — including self-reacquisition, so a
        holder that lost track of time gets a NEW token and its old one
        dies. Returns the lease dict, or None while another holder's
        lease is live."""
        with self._transition_lock:
            with self._conn_ctx() as conn:
                # liveness check and token bump must be ONE unit across
                # processes too (the SELECT alone runs in autocommit on a
                # file DB): two double-started agents must never both
                # conclude "expired" and both believe they acquired
                if not conn.in_transaction:
                    conn.execute("BEGIN IMMEDIATE")
                row = self._lease_row(conn, name)
                if (row is not None and row["holder"] != holder
                        and self._lease_age(row["renewed_at"]) < row["ttl"]):
                    return None
                key = f"lease_token:{name}"
                conn.execute(
                    "INSERT OR IGNORE INTO counters (k, v) VALUES (?, 0)",
                    (key,))
                conn.execute("UPDATE counters SET v=v+1 WHERE k=?", (key,))
                token = conn.execute(
                    "SELECT v FROM counters WHERE k=?", (key,)).fetchone()[0]
                now = _now()
                conn.execute(
                    "INSERT OR REPLACE INTO agent_leases "
                    "(name, holder, token, ttl, acquired_at, renewed_at) "
                    "VALUES (?,?,?,?,?,?)",
                    (name, holder, token, float(ttl), now, now))
                return self._lease_row(conn, name)

    def renew_lease(self, name: str, holder: str, token: int) -> bool:
        """Stamp renewed_at iff (holder, token) still own the lease.
        False means a newer acquisition exists (or the lease was
        released): the caller is stale and must demote itself."""
        return self.renew_leases([(name, token)], holder)[0]

    def renew_leases(self, renewals: list[tuple], holder: str) -> list[bool]:
        """Batch renewal: one transaction for every lease this holder
        keeps alive (a sharded agent renews all its shard leases + its
        presence row per heartbeat instead of K round-trips). Each entry
        is ``(name, token)``; returns per-entry success — False means
        that lease has a newer acquisition (or was released) and the
        holder must demote itself FOR THAT SHARD ONLY."""
        out: list[bool] = []
        with self._conn_ctx() as conn:
            now = _now()
            for name, token in renewals:
                cur = conn.execute(
                    "UPDATE agent_leases SET renewed_at=? "
                    "WHERE name=? AND holder=? AND token=?",
                    (now, name, holder, token))
                out.append(cur.rowcount > 0)
        return out

    def release_lease(self, name: str, holder: str, token: int) -> bool:
        """Explicit release on graceful shutdown — a successor acquires
        instantly instead of waiting out the TTL. Only the current
        (holder, token) may release; the token counter survives, so the
        next acquisition still gets a strictly newer token."""
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "DELETE FROM agent_leases "
                "WHERE name=? AND holder=? AND token=?",
                (name, holder, token))
        return cur.rowcount > 0

    def get_lease(self, name: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = self._lease_row(conn, name)
        if row is not None:
            row["expired"] = self._lease_age(row["renewed_at"]) >= row["ttl"]
        return row

    def claim_config(self, key: str, value: str) -> str:
        """First-writer-wins fleet setting: atomically record ``value``
        for ``key`` unless some agent already did, and return the WINNING
        value — every later claimant must conform to it. Backs the
        num_shards agreement check (a fleet hashing the run space with
        two different K values double-owns runs under valid fences)."""
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT OR IGNORE INTO control_config (key, value) "
                "VALUES (?, ?)", (key, str(value)))
            row = conn.execute(
                "SELECT value FROM control_config WHERE key=?",
                (key,)).fetchone()
        return row[0]

    def get_config(self, key: str) -> Optional[str]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT value FROM control_config WHERE key=?",
                (key,)).fetchone()
        return row[0] if row else None

    def set_config(self, key: str, value: str) -> None:
        """Operator override of a pinned fleet setting (e.g. resizing the
        shard partition): stop the WHOLE fleet first — agents adopt the
        pinned value only at start(), and a mixed fleet double-owns runs."""
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT OR REPLACE INTO control_config (key, value) "
                "VALUES (?, ?)", (key, str(value)))

    def list_leases(self, prefix: Optional[str] = None) -> list[dict]:
        """Every lease row (optionally name-prefixed: ``shard-`` for the
        work partition, ``agent-`` for live-agent presence), each with its
        ``expired`` flag — the input to shard fair-share balancing and the
        per-agent ownership table in ``/api/v1/stats``."""
        q = (f"SELECT {','.join(self._LEASE_COLS)} FROM agent_leases")
        args: list = []
        if prefix:
            q += " WHERE name LIKE ?"
            args.append(prefix.replace("%", "") + "%")
        q += " ORDER BY name"
        with self._conn_ctx() as conn:
            rows = conn.execute(q, args).fetchall()
        out = []
        for r in rows:
            d = dict(zip(self._LEASE_COLS, r))
            d["expired"] = self._lease_age(d["renewed_at"]) >= d["ttl"]
            out.append(d)
        return out

    def _check_fence(self, conn, fence) -> None:
        """Reject a fenced write whose token is no longer current. Atomic
        with the write it guards: python sqlite3 only opens the implicit
        transaction on DML — a bare SELECT runs in autocommit, which on a
        file DB shared by two processes would let a takeover commit
        BETWEEN this read and our write. BEGIN IMMEDIATE grabs the writer
        lock first, so the token read and the guarded write commit as one
        unit — there is no window where a stale agent's batch lands after
        a newer acquisition."""
        if fence is None:
            return
        if not conn.in_transaction:
            conn.execute("BEGIN IMMEDIATE")
        name, token = fence
        row = conn.execute(
            "SELECT token FROM agent_leases WHERE name=?", (name,)).fetchone()
        current = row[0] if row else None
        if current != token:
            self.stats["fence_rejections"] += 1
            # per-lease rejection family (lazy get-or-create): the sharded
            # soak asserts that a specific SHARD's stale owner was fenced,
            # not just that some rejection happened somewhere
            self.metrics.counter(
                "polyaxon_store_fence_rejections_by_lease_total",
                "Fenced writes rejected for a stale token, by lease name",
                labels={"lease": name}).inc()
            raise StaleLeaseError(name, token, current)

    # -- launch intents (write-ahead pod creation) -------------------------

    def record_launch_intent(self, run_uuid: str, lease_holder: Optional[str],
                             token: Optional[int],
                             lease_name: Optional[str] = None,
                             fence=None) -> dict:
        """Write-ahead row for a pod launch: bump the attempt counter, set
        state='intent', and stamp ``meta.owner = {lease_id, token,
        attempt}`` on the run — all in ONE transaction, BEFORE any cluster
        call. A crash after this commit but before the pods exist leaves
        state='intent' with no pods: the successor relaunches. A crash
        after :meth:`mark_launched` leaves state='launched': the successor
        adopts the live pods instead of creating a second set."""
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                prev = conn.execute(
                    "SELECT attempt FROM launch_intents WHERE run_uuid=?",
                    (run_uuid,)).fetchone()
                attempt = (prev[0] if prev else 0) + 1
                now = _now()
                conn.execute(
                    "INSERT OR REPLACE INTO launch_intents (run_uuid, "
                    "lease_name, lease_holder, token, attempt, state, "
                    "created_at, updated_at) VALUES (?,?,?,?,?,?,?,?)",
                    (run_uuid, lease_name, lease_holder, token, attempt,
                     "intent", now, now))
                self._stamp_owner(conn, run_uuid, lease_holder, token, attempt)
                self.stats["launch_intents"] += 1
        return {"run_uuid": run_uuid, "attempt": attempt, "state": "intent",
                "lease_holder": lease_holder, "token": token}

    def mark_launched(self, run_uuid: str, fence=None) -> None:
        """Flip the intent to state='launched' AFTER the cluster accepted
        every manifest — the pods exist now; a successor must adopt."""
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            conn.execute(
                "UPDATE launch_intents SET state='launched', updated_at=? "
                "WHERE run_uuid=?", (_now(), run_uuid))

    def adopt_launch(self, run_uuid: str, lease_holder: Optional[str],
                     token: Optional[int], fence=None) -> None:
        """Re-own a live pod set after an agent restart: update the intent
        row and meta.owner to the NEW lease without bumping the attempt
        counter — adoption is not a launch."""
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                now = _now()
                row = conn.execute(
                    "SELECT attempt FROM launch_intents WHERE run_uuid=?",
                    (run_uuid,)).fetchone()
                attempt = row[0] if row else 1
                conn.execute(
                    "INSERT OR REPLACE INTO launch_intents (run_uuid, "
                    "lease_name, lease_holder, token, attempt, state, "
                    "created_at, updated_at) VALUES (?,?,?,?,?,'launched',?,?)",
                    (run_uuid, None, lease_holder, token, attempt, now, now))
                self._stamp_owner(conn, run_uuid, lease_holder, token, attempt)

    def get_launch_intent(self, run_uuid: str) -> Optional[dict]:
        cols = ("run_uuid", "lease_name", "lease_holder", "token", "attempt",
                "state", "created_at", "updated_at")
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(cols)} FROM launch_intents "
                "WHERE run_uuid=?", (run_uuid,)).fetchone()
        return dict(zip(cols, row)) if row else None

    def _stamp_owner(self, conn, run_uuid: str, lease_holder, token,
                     attempt: int) -> None:
        row = conn.execute(
            "SELECT meta FROM runs WHERE uuid=?", (run_uuid,)).fetchone()
        if row is None:
            return
        meta = json.loads(row[0]) if row[0] else {}
        meta["owner"] = {"lease_id": lease_holder, "token": token,
                         "attempt": attempt}
        conn.execute(
            "UPDATE runs SET meta=?, updated_at=?, change_seq=? WHERE uuid=?",
            (json.dumps(meta), _now(), self._bump_seq(conn), run_uuid))

    # -- runs --------------------------------------------------------------

    _RUN_COLS = (
        "uuid", "project", "name", "kind", "status", "spec", "compiled",
        "inputs", "outputs", "meta", "tags", "original_uuid", "cloning_kind",
        "pipeline_uuid", "created_by", "created_at", "updated_at",
        "started_at", "finished_at", "heartbeat_at", "change_seq",
    )
    _JSON_COLS = {"spec", "compiled", "inputs", "outputs", "meta", "tags"}

    def _bump_seq(self, conn, n: int = 1) -> int:
        """Advance the change counter by ``n`` inside the CURRENT write
        transaction and return the new top value. The UPDATE acquires
        SQLite's single-writer lock, so assigned seqs are strictly ordered
        with commit order — the property ?since= needs to never lose a
        row (a wall-clock stamp can predate a competing commit)."""
        conn.execute("UPDATE counters SET v=v+? WHERE k='change_seq'", (n,))
        return conn.execute(
            "SELECT v FROM counters WHERE k='change_seq'").fetchone()[0]

    def current_seq(self) -> int:
        """Latest committed change_seq (snapshot-consistent bootstrap token
        for incremental fetches: an in-flight writer's bump is invisible
        until its commit, so its rows always land AFTER this value)."""
        with self._conn_ctx() as conn:
            return conn.execute(
                "SELECT v FROM counters WHERE k='change_seq'").fetchone()[0]

    def _row_to_run(self, row) -> dict:
        self.stats["runs_deserialized"] += 1
        d = dict(zip(self._RUN_COLS, row))
        for c in self._JSON_COLS:
            d[c] = json.loads(d[c]) if d[c] else None
        return d

    @staticmethod
    def _params_to_inputs(spec: dict) -> Optional[dict]:
        """A run's queryable inputs default to its bound param values
        (upstream stored resolved params on the run row; compare/sort
        read them). Ref params carry an unresolved context expression as
        their value and context_only params aren't inputs — skip both."""
        params = spec.get("params") or {}
        out = {}
        for k, v in params.items():
            if isinstance(v, dict):
                if v.get("ref") or v.get("context_only") or v.get("contextOnly"):
                    continue
                out[k] = v.get("value")
            else:
                out[k] = v
        return out or None

    def create_run(
        self,
        project: str,
        spec: Optional[dict] = None,
        name: Optional[str] = None,
        kind: Optional[str] = None,
        inputs: Optional[dict] = None,
        meta: Optional[dict] = None,
        tags: Optional[list] = None,
        uuid: Optional[str] = None,
        original_uuid: Optional[str] = None,
        cloning_kind: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        created_by: Optional[str] = None,
        fence=None,
    ) -> dict:
        return self.create_runs(project, [dict(
            spec=spec, name=name, kind=kind, inputs=inputs, meta=meta,
            tags=tags, uuid=uuid, original_uuid=original_uuid,
            cloning_kind=cloning_kind, pipeline_uuid=pipeline_uuid,
            created_by=created_by,
        )], fence=fence)[0]

    def create_runs(self, project: str, runs: list[dict],
                    fence=None) -> list[dict]:
        """Create many runs in ONE transaction (DAG/matrix fan-out: a
        16-wide suggestion batch is one commit, not 32). Each entry takes
        the same keyword fields as ``create_run``. Listeners fire after the
        commit, once per run, in order. ``fence=(lease_name, token)``
        rejects the whole batch with :class:`StaleLeaseError` when the
        token is no longer current — a stale agent's pipeline driver must
        not fan out children after a takeover."""
        self.create_project(project)
        rows, conds = [], []
        uuids: list[str] = []
        parents: dict[str, Optional[dict]] = {}  # one lookup per batch
        for r in runs:
            spec = r.get("spec")
            inputs = r.get("inputs")
            if inputs is None and spec:
                # one place for every creation path (CLI, client, server, DAG
                # and schedule children, tuner trials pass explicit inputs)
                inputs = self._params_to_inputs(spec)
            created_by = r.get("created_by")
            if created_by is None and r.get("pipeline_uuid"):
                # pipeline children (DAG stages, sweep trials, schedule runs)
                # inherit their parent's owner — ownership filtering must not
                # split a user's pipeline from its stages (review r5)
                puid = r["pipeline_uuid"]
                if puid not in parents:
                    parents[puid] = self.get_run(puid)
                if parents[puid]:
                    created_by = parents[puid].get("created_by")
            run_uuid = r.get("uuid") or uuid_mod.uuid4().hex
            uuids.append(run_uuid)
            rows.append((
                run_uuid, project, r.get("name"), r.get("kind"),
                V1Statuses.CREATED.value,
                json.dumps(spec) if spec else None,
                json.dumps(inputs) if inputs else None,
                json.dumps(r.get("meta")) if r.get("meta") else None,
                json.dumps(r.get("tags")) if r.get("tags") else None,
                r.get("original_uuid"), r.get("cloning_kind"),
                r.get("pipeline_uuid"), created_by,
            ))
            conds.append((
                run_uuid,
                json.dumps(V1StatusCondition.get_condition(V1Statuses.CREATED).to_dict()),
            ))
        t0 = time.perf_counter()
        with self._conn_ctx() as conn:
            try:
                self._check_fence(conn, fence)
                # timestamps + change seqs assigned INSIDE the write
                # transaction (the seq bump takes the writer lock), so
                # seq order matches commit order and ?since= pollers can
                # never skip a row committed after their snapshot
                now = _now()
                top = self._bump_seq(conn, len(rows))
                first = top - len(rows) + 1
                conn.executemany(
                    "INSERT INTO runs (uuid, project, name, kind, status, spec, inputs, meta, tags,"
                    " original_uuid, cloning_kind, pipeline_uuid, created_by, created_at, updated_at,"
                    " change_seq)"
                    " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                    [row + (now, now, first + i) for i, row in enumerate(rows)])
                conn.executemany(
                    "INSERT INTO status_conditions (run_uuid, condition, created_at) VALUES (?,?,?)",
                    [cond + (now,) for cond in conds])
            except BaseException:
                # same hazard transition_many guards against: a mid-batch
                # failure (e.g. duplicate uuid) must not strand earlier
                # rows uncommitted for the next unrelated commit to flush
                # as ghost runs that never fired the change feed
                conn.rollback()
                raise
        self._h_write.observe(time.perf_counter() - t0)
        # creation flows through the same feed as transitions so a
        # subscribed agent learns about new runs without scanning
        self._notify_listeners(
            [(u, V1Statuses.CREATED.value) for u in uuids])
        by_uuid = {r["uuid"]: r for r in self.get_runs(uuids)}
        return [by_uuid[u] for u in uuids]

    def _notify_listeners(self, events: list[tuple[str, str]]) -> None:
        """Fire ``(uuid, status)`` feed events in order. Always called
        AFTER the commit and outside any store lock — listeners may read
        the store."""
        for run_uuid, status in events:
            for listener in self._transition_listeners:
                try:
                    listener(run_uuid, status)
                except Exception:
                    import traceback

                    traceback.print_exc()

    def get_run(self, uuid: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._RUN_COLS)} FROM runs WHERE uuid=?", (uuid,)
            ).fetchone()
        return self._row_to_run(row) if row else None

    def get_runs(self, uuids: list[str]) -> list[dict]:
        """Fetch many runs by uuid in ONE query (the agent's dirty pass
        reads its whole dirty set this way). Missing uuids are silently
        absent; order is unspecified."""
        if not uuids:
            return []
        out: list[dict] = []
        with self._conn_ctx() as conn:
            # chunked: SQLite's default parameter cap is 999
            for i in range(0, len(uuids), 500):
                chunk = uuids[i:i + 500]
                rows = conn.execute(
                    f"SELECT {','.join(self._RUN_COLS)} FROM runs "
                    f"WHERE uuid IN ({','.join('?' * len(chunk))})",
                    chunk).fetchall()
                out += rows
        return [self._row_to_run(r) for r in out]

    @staticmethod
    def _runs_where(
        project=None, status=None, statuses=None, pipeline_uuid=None,
        created_by=None,
    ) -> tuple[str, list]:
        q, args = " WHERE 1=1", []
        if project:
            q += " AND project=?"
            args.append(project)
        if created_by:
            q += " AND created_by=?"
            args.append(created_by)
        if status:
            q += " AND status=?"
            args.append(status)
        if statuses:
            q += f" AND status IN ({','.join('?' * len(statuses))})"
            args.extend(statuses)
        if pipeline_uuid:
            q += " AND pipeline_uuid=?"
            args.append(pipeline_uuid)
        return q, args

    @staticmethod
    def run_cursor(run: dict) -> str:
        """Opaque keyset-pagination cursor for a listing row."""
        return f"{run['created_at']}|{run['uuid']}"

    @staticmethod
    def since_token(run: dict) -> str:
        """Resume token for incremental (``since``) fetches: the row's
        commit-ordered change_seq."""
        return str(run["change_seq"])

    def list_runs(
        self,
        project: Optional[str] = None,
        status: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        limit: int = 100,
        offset: int = 0,
        statuses: Optional[list[str]] = None,
        created_by: Optional[str] = None,
        order: str = "desc",
        cursor: Optional[str] = None,
        since: Optional[str] = None,
    ) -> list[dict]:
        """List runs, newest first by default (``order="asc"`` = FIFO).

        ``cursor`` (from :meth:`run_cursor`) keyset-paginates: rows strictly
        after the cursor position in the current order — O(page) however
        deep the listing, unlike OFFSET which scans every skipped row.
        ``since`` switches to incremental mode: rows whose commit-ordered
        ``change_seq`` is after the token (an int string — the bootstrap is
        :meth:`current_seq`, pages resume from :meth:`since_token` of the
        last delivered row), ordered by change_seq ascending, so pollers
        fetch O(delta) instead of O(all-runs) and can never lose a row to
        a stamp-before-commit race (overrides order/cursor)."""
        where, args = self._runs_where(
            project=project, status=status, statuses=statuses,
            pipeline_uuid=pipeline_uuid, created_by=created_by)
        q = f"SELECT {','.join(self._RUN_COLS)} FROM runs" + where
        if since is not None:
            q += " AND change_seq>? ORDER BY change_seq ASC LIMIT ? OFFSET ?"
            args += [int(since), limit, offset]
        else:
            if order not in ("desc", "asc"):
                raise ValueError(f"bad order {order!r}")
            if cursor is not None:
                c_at, _, c_uuid = cursor.partition("|")
                cmp = "<" if order == "desc" else ">"
                q += (f" AND (created_at{cmp}? OR "
                      f"(created_at=? AND uuid{cmp}?))")
                args += [c_at, c_at, c_uuid]
            # uuid tiebreak keeps the cursor total order stable when two
            # runs share a created_at microsecond (bulk create_runs does)
            q += (f" ORDER BY created_at {order.upper()}, "
                  f"uuid {order.upper()} LIMIT ? OFFSET ?")
            args += [limit, offset]
        with self._conn_ctx() as conn:
            rows = conn.execute(q, args).fetchall()
        runs = [self._row_to_run(r) for r in rows]
        # heartbeat staleness used to be observable only by the reaper
        # (ISSUE 5 satellite): stamp the age onto in-flight listing rows so
        # the dashboard can badge zombie-suspect runs without a second
        # query. Derived (never stored), and only present where it means
        # something — terminal/queued rows keep their exact shape.
        for d in runs:
            if d["status"] in (V1Statuses.STARTING.value,
                               V1Statuses.RUNNING.value):
                age = age_seconds(d.get("heartbeat_at") or d.get("started_at"))
                if age is not None:
                    d["heartbeat_age_s"] = round(age, 3)
        return runs

    def count_runs(
        self,
        project: Optional[str] = None,
        status: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        statuses: Optional[list[str]] = None,
        created_by: Optional[str] = None,
    ) -> int:
        """Total rows matching the listing filters (pagination UIs)."""
        where, args = self._runs_where(
            project=project, status=status, statuses=statuses,
            pipeline_uuid=pipeline_uuid, created_by=created_by)
        with self._conn_ctx() as conn:
            return conn.execute(
                "SELECT COUNT(*) FROM runs" + where, args).fetchone()[0]

    def update_run(self, uuid: str, fence=None, **fields: Any) -> Optional[dict]:
        sets, args = [], []
        for k, v in fields.items():
            if k not in self._RUN_COLS or k in ("uuid", "change_seq"):
                raise ValueError(f"bad run field {k!r}")
            if k in self._JSON_COLS and v is not None and not isinstance(v, str):
                v = json.dumps(v)
            sets.append(f"{k}=?")
            args.append(v)
        sets.append("updated_at=?")
        args.append(_now())
        sets.append("change_seq=?")
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            args.append(self._bump_seq(conn))
            conn.execute(f"UPDATE runs SET {','.join(sets)} WHERE uuid=?",
                         args + [uuid])
        return self.get_run(uuid)

    def merge_outputs(self, uuid: str, outputs: dict,
                      fence=None) -> Optional[dict]:
        # serialize the read-modify-write: concurrent writers (API
        # post_outputs, agent _collect_outputs, tuner merge) must not drop keys
        with self._transition_lock:
            run = self.get_run(uuid)
            if run is None:
                return None
            merged = dict(run.get("outputs") or {})
            merged.update(outputs)
            return self.update_run(uuid, fence=fence, outputs=merged)

    def heartbeat(self, uuid: str) -> bool:
        """Renew a run's liveness lease (zombie-reaper input). Cheap direct
        UPDATE — no listeners fire, no updated_at churn."""
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "UPDATE runs SET heartbeat_at=? WHERE uuid=?", (_now(), uuid))
        return cur.rowcount > 0

    def delete_run(self, uuid: str) -> bool:
        with self._conn_ctx() as conn:
            cur = conn.execute("DELETE FROM runs WHERE uuid=?", (uuid,))
            conn.execute("DELETE FROM status_conditions WHERE run_uuid=?", (uuid,))
            conn.execute("DELETE FROM lineage WHERE run_uuid=?", (uuid,))
            conn.execute("DELETE FROM launch_intents WHERE run_uuid=?", (uuid,))
        return cur.rowcount > 0

    # -- statuses ----------------------------------------------------------

    def transition(
        self, uuid: str, status: str, reason: Optional[str] = None,
        message: Optional[str] = None, force: bool = False, fence=None,
    ) -> tuple[Optional[dict], bool]:
        """Apply a status transition if legal. Returns (run, changed).
        Atomic: the check + condition insert + status update hold one lock so
        concurrent writers (agent vs executor threads) cannot interleave —
        e.g. a late 'failed' from a killed process must not overwrite
        'stopped'."""
        return self.transition_many([(uuid, status, reason, message, force)],
                                    fence=fence)[0]

    def _get_run_conn(self, conn, uuid: str) -> Optional[dict]:
        row = conn.execute(
            f"SELECT {','.join(self._RUN_COLS)} FROM runs WHERE uuid=?", (uuid,)
        ).fetchone()
        return self._row_to_run(row) if row else None

    def transition_many(
        self, transitions: list[tuple], fence=None,
    ) -> list[tuple[Optional[dict], bool]]:
        """Apply many status transitions in ONE lock hold + ONE commit.

        ``transitions``: ``(uuid, status[, reason[, message[, force]]])``
        tuples, applied in order — later entries see earlier ones (the
        reconciler's restart path walks running -> retrying -> queued ->
        scheduled on one run). Returns (run, changed) per entry, same
        semantics as :meth:`transition`. Listeners fire after the batch
        commits, in order, only for applied transitions — so a burst of
        lifecycle updates is one fsync, not 3 transactions each.
        ``fence=(lease_name, token)`` rejects the whole batch with
        :class:`StaleLeaseError` when a newer lease acquisition exists —
        a stale agent's promotion wave cannot land after a takeover."""
        results: list[tuple[Optional[dict], bool]] = []
        applied: list[tuple[str, str]] = []
        sched_ages: list[float] = []
        t0 = time.perf_counter()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                try:
                    self._check_fence(conn, fence)
                    self._transition_batch(conn, transitions, results, applied,
                                           sched_ages)
                except BaseException:
                    # a mid-batch error (bad status string, corrupt row)
                    # must not strand earlier entries' writes uncommitted
                    # on the shared connection — the next unrelated commit
                    # would flush them WITHOUT their listeners ever firing
                    conn.rollback()
                    applied.clear()
                    sched_ages.clear()
                    raise
        self._h_write.observe(time.perf_counter() - t0)
        # schedule-latency samples flush only after the batch COMMITS: a
        # rolled-back batch also rolls back started_at, so the retried
        # RUNNING edge would otherwise observe the same run twice
        for age in sched_ages:
            self._h_sched.observe(age)
        # observers run OUTSIDE the lock (they may read the store) and only
        # for transitions that actually happened — hooks keyed off rejected
        # late reports (a killed process's 'failed' after 'stopped') never
        # fire with the wrong status
        self._notify_listeners(applied)
        return results

    def _transition_batch(self, conn, transitions, results, applied,
                          sched_ages: Optional[list] = None) -> None:
        for t in transitions:
            uuid, status = t[0], t[1]
            reason = t[2] if len(t) > 2 else None
            message = t[3] if len(t) > 3 else None
            force = bool(t[4]) if len(t) > 4 else False
            run = self._get_run_conn(conn, uuid)
            if run is None:
                results.append((None, False))
                continue
            src = V1Statuses(run["status"])
            dst = V1Statuses(status)
            if (not force or src in DONE_STATUSES) and not can_transition(src, dst):
                results.append((run, False))
                continue
            cond = V1StatusCondition.get_condition(
                dst, reason=reason, message=message)
            now = _now()
            sets = ["status=?", "updated_at=?", "change_seq=?"]
            args: list[Any] = [dst.value, now, self._bump_seq(conn)]
            if dst == V1Statuses.RUNNING and not run.get("started_at"):
                sets.append("started_at=?")
                args.append(now)
                # schedule latency stamped with the FIRST running edge
                # (retries don't re-observe: started_at is already set);
                # the caller observes it only after the batch commits —
                # the exact created->running interval scripts/
                # sched_bench.py measures from its listener
                age = age_seconds(run.get("created_at"))
                if age is not None and sched_ages is not None:
                    sched_ages.append(age)
            if is_done(dst):
                sets.append("finished_at=?")
                args.append(now)
            conn.execute(
                "INSERT INTO status_conditions (run_uuid, condition, created_at) VALUES (?,?,?)",
                (uuid, json.dumps(cond.to_dict()), now),
            )
            conn.execute(
                f"UPDATE runs SET {','.join(sets)} WHERE uuid=?",
                args + [uuid])
            results.append((self._get_run_conn(conn, uuid), True))
            applied.append((uuid, dst.value))

    def add_transition_listener(self, fn) -> None:
        """Register ``fn(uuid, new_status)`` called after every applied
        transition (any writer: agent, executor callbacks, API clients)."""
        self._transition_listeners.append(fn)

    def find_cached_run(self, project: str, cache_key: str) -> Optional[dict]:
        """Most recent succeeded run in ``project`` whose meta.cache_key
        matches — SQL-side so the lookup is one row, not a page scan."""
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._RUN_COLS)} FROM runs "
                "WHERE project=? AND status='succeeded' "
                "AND json_extract(meta, '$.cache_key')=? "
                "ORDER BY created_at DESC LIMIT 1",
                (project, cache_key),
            ).fetchone()
        return self._row_to_run(row) if row else None

    def get_statuses(self, uuid: str) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT condition FROM status_conditions WHERE run_uuid=? ORDER BY id",
                (uuid,),
            ).fetchall()
        return [json.loads(r[0]) for r in rows]

    # -- lineage -----------------------------------------------------------

    def add_lineage(self, uuid: str, artifact: dict) -> None:
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT INTO lineage (run_uuid, name, artifact) VALUES (?,?,?)",
                (uuid, artifact.get("name"), json.dumps(artifact)),
            )

    def get_lineage(self, uuid: str) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT artifact FROM lineage WHERE run_uuid=? ORDER BY id", (uuid,)
            ).fetchall()
        return [json.loads(r[0]) for r in rows]


class FencedStore:
    """Write-fencing proxy over a :class:`Store` (or any store-shaped
    wrapper, e.g. the chaos FaultyStore).

    Every lifecycle write — run creation, transition batches, run updates,
    launch-intent stamping — is stamped with the caller's CURRENT lease
    fence, read lazily per call from ``fence_source`` (None = no lease
    held = unfenced, preserving direct-call test semantics). The agent
    hands this proxy to everything that writes on its behalf (pipeline
    drivers, the zombie reaper, executor callbacks), so a takeover fences
    out every code path at once instead of each call site remembering to.

    Sharded mode (ISSUE 6): ``fence_source`` may return a CALLABLE
    ``run_uuid -> fence`` instead of a fence tuple. Each write is then
    stamped with the token of the shard that owns THAT run, so a stale
    shard owner is write-rejected per-shard, not per-agent:

    - single-run verbs resolve the fence from their uuid argument;
    - ``create_run(s)`` resolve it from the entries' ``pipeline_uuid`` —
      the authority to fan out children is ownership of the PARENT
      pipeline's shard (parentless creations are client-equivalent and
      go unfenced);
    - ``transition_many`` splits the batch into per-shard sub-batches
      BEFORE the transaction: a fence rejection from a concurrent shard
      owner rejects only that shard's sub-batch (its entries come back
      as ``(current row, False)``) while every other sub-batch commits.

    ``on_stale`` fires (once per rejection, outside any store lock). With
    a tuple fence source it is called with no arguments and the
    :class:`StaleLeaseError` propagates (pre-shard semantics); with a
    callable source it receives the rejected LEASE NAME so the caller can
    demote exactly that shard."""

    _FENCED = ("create_run", "create_runs", "transition", "transition_many",
               "update_run", "merge_outputs", "record_launch_intent",
               "mark_launched", "adopt_launch")

    def __init__(self, inner, fence_source, on_stale=None):
        import inspect

        self._inner = inner
        self._fence_source = fence_source
        self._on_stale = on_stale
        self._on_stale_takes_name = False
        if on_stale is not None:
            try:
                self._on_stale_takes_name = bool(
                    inspect.signature(on_stale).parameters)
            except (TypeError, ValueError):
                pass

    def _notify_stale(self, lease_name: Optional[str]) -> None:
        if self._on_stale is None:
            return
        if self._on_stale_takes_name:
            self._on_stale(lease_name)
        else:
            self._on_stale()

    def _resolve_fence(self, verb: str, src, a: tuple, kw: dict):
        """Concrete ``(name, token)`` (or None) for one call under a
        callable (sharded) fence source."""
        if verb in ("create_run", "create_runs"):
            if verb == "create_runs":
                entries = a[1] if len(a) > 1 else kw.get("runs") or []
            else:
                entries = [kw]
            puid = next((r.get("pipeline_uuid") for r in entries
                         if r.get("pipeline_uuid")), None)
            return src(puid) if puid else None
        uuid = a[0] if a else kw.get("uuid") or kw.get("run_uuid")
        return src(uuid)

    def transition_many(self, transitions: list[tuple], fence=None,
                        **kw: Any) -> list[tuple[Optional[dict], bool]]:
        src = self._fence_source() if fence is None else fence
        if not callable(src):
            try:
                return self._inner.transition_many(transitions, fence=src,
                                                   **kw)
            except StaleLeaseError as e:
                self._notify_stale(e.lease_name)
                raise
        # sharded: one sub-batch (one lock hold + one commit) per distinct
        # shard fence, in first-appearance order; a stale sub-batch is
        # rejected alone and reported as unapplied
        groups: dict = {}
        order: list = []
        for i, t in enumerate(transitions):
            f = src(t[0])
            if f not in groups:
                groups[f] = []
                order.append(f)
            groups[f].append((i, t))
        results: list = [None] * len(transitions)
        for f in order:
            entries = groups[f]
            try:
                out = self._inner.transition_many(
                    [t for _, t in entries], fence=f, **kw)
            except StaleLeaseError:
                self._notify_stale(f[0] if f else None)
                for i, t in entries:
                    results[i] = (self._inner.get_run(t[0]), False)
                continue
            for (i, _), r in zip(entries, out):
                results[i] = r
        return results

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in self._FENCED and callable(attr):
            def _fenced(*a: Any, _attr=attr, _name=name, **kw: Any) -> Any:
                if "fence" not in kw:
                    src = self._fence_source()
                    kw["fence"] = (self._resolve_fence(_name, src, a, kw)
                                   if callable(src) else src)
                try:
                    return _attr(*a, **kw)
                except StaleLeaseError as e:
                    self._notify_stale(e.lease_name)
                    raise

            return _fenced
        return attr
