"""SQLite-backed persistence for projects/runs/statuses (the API service
DB — upstream used Django+Postgres, SURVEY.md §2 "API service"; SQLite is
the local/agent deployment default and is WAL-mode safe across the API and
scheduler threads)."""

from __future__ import annotations

import abc
import datetime
import errno
import json
import sqlite3
import threading
import time
import uuid as uuid_mod
import zlib
from typing import Any, Optional

from ..federation.health import CLUSTER_HEALTH_PREFIX
from ..resilience.heartbeat import age_seconds
from ..schemas.statuses import DONE_STATUSES, V1StatusCondition, V1Statuses, can_transition, is_done

_SCHEMA = """
CREATE TABLE IF NOT EXISTS projects (
    name TEXT PRIMARY KEY,
    description TEXT,
    created_at TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS runs (
    uuid TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    name TEXT,
    kind TEXT,
    status TEXT NOT NULL,
    spec TEXT,
    compiled TEXT,
    inputs TEXT,
    outputs TEXT,
    meta TEXT,
    tags TEXT,
    original_uuid TEXT,
    cloning_kind TEXT,
    pipeline_uuid TEXT,
    created_by TEXT,
    tenant TEXT,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL,
    started_at TEXT,
    finished_at TEXT,
    heartbeat_at TEXT,
    heartbeat_step INTEGER,
    heartbeat_step_at TEXT,
    change_seq INTEGER
);
-- monotone change counter: bumped INSIDE every write transaction (the
-- UPDATE takes SQLite's single-writer lock, so seq order == commit
-- order), which is what makes ?since= incremental fetches loss-free —
-- wall-clock timestamps can be stamped before a competing commit lands
CREATE TABLE IF NOT EXISTS counters (
    k TEXT PRIMARY KEY,
    v INTEGER NOT NULL
);
INSERT OR IGNORE INTO counters (k, v) VALUES ('change_seq', 0);
CREATE INDEX IF NOT EXISTS idx_runs_project ON runs (project, created_at);
CREATE INDEX IF NOT EXISTS idx_runs_status ON runs (status);
-- queue pops: the agent lists one status ordered by created_at (FIFO);
-- without the composite index SQLite picks idx_runs_status then sorts
CREATE INDEX IF NOT EXISTS idx_runs_status_created ON runs (status, created_at);
-- (idx_runs_change_seq is created post-migration in __init__: on a
-- pre-r7 db the column does not exist yet when this script runs)
CREATE INDEX IF NOT EXISTS idx_runs_pipeline ON runs (pipeline_uuid);
CREATE TABLE IF NOT EXISTS status_conditions (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_uuid TEXT NOT NULL,
    condition TEXT NOT NULL,
    created_at TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_conditions_run ON status_conditions (run_uuid);
CREATE TABLE IF NOT EXISTS lineage (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_uuid TEXT NOT NULL,
    name TEXT,
    artifact TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_lineage_run ON lineage (run_uuid);
CREATE TABLE IF NOT EXISTS tokens (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    token_hash TEXT NOT NULL UNIQUE,
    project TEXT,
    label TEXT,
    created_at TEXT NOT NULL,
    revoked INTEGER NOT NULL DEFAULT 0
);
-- control-plane crash safety (docs/RESILIENCE.md "Control-plane crash
-- matrix"): one row per named lease (the scheduler holds "scheduler").
-- ``token`` is the fencing token — monotonic across acquisitions AND
-- releases (the counter lives in ``counters`` under lease_token:<name>,
-- so a delete+reacquire can never reissue an old token). Agent-side
-- writes carry (name, token) and are rejected when the row's token
-- differs: a stale agent that wakes from a GC pause can observe but
-- not mutate.
CREATE TABLE IF NOT EXISTS agent_leases (
    name TEXT PRIMARY KEY,
    holder TEXT NOT NULL,
    token INTEGER NOT NULL,
    ttl REAL NOT NULL,
    acquired_at TEXT NOT NULL,
    renewed_at TEXT NOT NULL
);
-- write-ahead launch intents: the agent records (lease, token, attempt)
-- BEFORE asking the cluster for pods, so a restarted agent can tell
-- "intent recorded, pod never created" (safe to relaunch) from
-- "pods launched, row stale" (adopt — never a duplicate pod set).
CREATE TABLE IF NOT EXISTS launch_intents (
    run_uuid TEXT PRIMARY KEY,
    lease_name TEXT,
    lease_holder TEXT,
    token INTEGER,
    attempt INTEGER NOT NULL,
    state TEXT NOT NULL,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL
);
-- write-ahead trial intents (ISSUE 19): the sweep driver records the
-- (sweep_uuid, trial_index, params_hash) of a suggestion window BEFORE
-- create_runs, so a successor adopting the sweep can tell "intent
-- recorded, trials never created" (re-derive the same suggestion — the
-- sampler is seeded per (sweep_uuid, trial_index) — and launch exactly
-- once) from "trials created, marker stale" (adopt the child rows).
-- params_hash is the replay audit: a re-derived suggestion that hashes
-- differently is a determinism bug and fails loudly, never silently
-- launching a divergent trial under a recorded index.
-- suggestion is the full {params, meta} JSON: recovery launches the
-- RECORDED window verbatim (exactly-once even when other trials finished
-- between the corpse's propose and the successor's replay), while
-- params_hash audits that a re-derived proposal from the same history
-- agrees (the per-(sweep_uuid, trial_index) seeding contract).
CREATE TABLE IF NOT EXISTS trial_intents (
    sweep_uuid TEXT NOT NULL,
    trial_index INTEGER NOT NULL,
    params_hash TEXT,
    suggestion TEXT,
    run_uuid TEXT,
    state TEXT NOT NULL,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL,
    PRIMARY KEY (sweep_uuid, trial_index)
);
-- per-tenant chip quotas (ISSUE 15): the admission/fair-share budget the
-- agent walks against. One row per tenant; absent tenants fall back to
-- the 'default' row (or unlimited when none exists) — loudly, via a
-- status condition + counter, never a KeyError in the scheduler pass.
CREATE TABLE IF NOT EXISTS quotas (
    tenant TEXT PRIMARY KEY,
    chips INTEGER NOT NULL,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL
);
-- cluster registry (ISSUE 16): one row per named cluster backend an agent
-- owns. Capacity/region/chip_type feed placement + spillover decisions;
-- liveness is NOT a column — it is the ``cluster-health-<name>`` TTL
-- lease in agent_leases, renewed by the owning agent, so "healthy" can
-- never go stale in a crashed writer's row. Replicated like quotas.
CREATE TABLE IF NOT EXISTS clusters (
    name TEXT PRIMARY KEY,
    region TEXT,
    chip_type TEXT,
    capacity INTEGER NOT NULL DEFAULT 0,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL
);
-- first-writer-wins control-plane settings the whole fleet must agree
-- on (num_shards: two agents hashing the run space with different K
-- would BOTH own some runs under valid fences — duplicate launches the
-- per-shard fencing cannot catch).
CREATE TABLE IF NOT EXISTS control_config (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
-- store survivability (docs/RESILIENCE.md "Store crash matrix"): a
-- durable, commit-ordered changelog of every replicated write. seq rides
-- the SAME change_seq counter the ?since= feed uses (bumped inside the
-- write transaction under the writer lock), so changelog order == commit
-- order and a standby tailing it can never observe rows out of order or
-- lose one to a stamp-before-commit race. agent_leases are deliberately
-- NOT replicated: promotion bumps the store epoch, which folds into
-- every fencing token, so pre-failover leases die with the primary.
CREATE TABLE IF NOT EXISTS changelog (
    seq INTEGER PRIMARY KEY,
    epoch INTEGER NOT NULL,
    op TEXT NOT NULL,
    payload TEXT NOT NULL,
    created_at TEXT NOT NULL
);
-- SLO alert state machine (ISSUE 20): one row per alert name, written
-- only through the fenced upsert_alert/resolve_alert verbs so alert
-- edges are exactly-once across agent takeovers, like any run
-- transition. pending_at restarts per episode (dwell timing);
-- last_notified_at is the notification dedup/re-notify watermark and
-- rides the SAME fenced write as the transition it announces.
-- Replicated through the changelog: a promoted standby serves the alert
-- table the primary committed.
CREATE TABLE IF NOT EXISTS alerts (
    name TEXT PRIMARY KEY,
    slo TEXT,
    state TEXT NOT NULL,
    severity TEXT,
    value REAL,
    reason TEXT,
    labels TEXT,
    transitions INTEGER NOT NULL DEFAULT 0,
    first_at TEXT NOT NULL,
    pending_at TEXT,
    fired_at TEXT,
    resolved_at TEXT,
    last_notified_at TEXT,
    updated_at TEXT NOT NULL
);
INSERT OR IGNORE INTO counters (k, v) VALUES ('store_epoch', 0);
INSERT OR IGNORE INTO counters (k, v) VALUES ('changelog_floor', 0);
"""


SHARD_PREFIX = "shard-"
AGENT_PREFIX = "agent-"  # presence leases: one per live agent, self-named


def shard_index(run_uuid: str, num_shards: int) -> int:
    """Stable shard assignment for a run: crc32 of the uuid bytes mod K.

    Stability is load-bearing — every agent (and every incarnation of an
    agent, across processes and restarts) must map a uuid to the SAME
    shard, because the shard name keys both the lease that authorizes
    writes to the run and which agent's wait queue it lives in."""
    return zlib.crc32(run_uuid.encode("utf-8")) % max(int(num_shards), 1)


def shard_lease_names(num_shards: int) -> list[str]:
    """The lease names of a K-shard control plane: shard-0 .. shard-K-1."""
    return [f"{SHARD_PREFIX}{i}" for i in range(max(int(num_shards), 1))]


def shard_ownership(rows: list[dict]) -> tuple[list[dict], dict]:
    """Split a ``list_leases()`` result into the work-partition view
    served by ``GET /api/v1/stats`` and ``polyaxon status``: (work lease
    rows, ``{holder: [lease names]}`` for the live owners). Presence rows
    (``agent-*``) are fleet membership, not work — excluded; expired rows
    appear in the list (orphaned, awaiting adoption) but own nothing."""
    shards = [r for r in rows if not r["name"].startswith(AGENT_PREFIX)]
    owners: dict = {}
    for r in shards:
        if not r["expired"]:
            owners.setdefault(r["holder"], []).append(r["name"])
    return shards, owners


class StaleLeaseError(RuntimeError):
    """A fenced write carried a token older than the current lease — the
    writer lost its lease (TTL takeover, double-start, explicit release)
    and must stop mutating. The API surfaces this as HTTP 409."""

    def __init__(self, name: str, token: Optional[int],
                 current: Optional[int]):
        self.lease_name = name
        self.token = token
        self.current = current
        super().__init__(
            f"stale lease token {token} for lease {name!r} "
            f"(current: {current})")


# Fencing tokens fold the store epoch into their high bits: promotion
# (store failover) bumps the epoch, so every token minted by the NEW
# primary is strictly greater than — and can never collide with — any
# token the dead primary handed out, even ones minted after the last
# replicated changelog row. Epoch 0 tokens are the bare counter (the
# pre-failover deployments' values, byte-compatible).
EPOCH_STRIDE = 1 << 40


def token_epoch(token: int) -> int:
    """The store epoch a fencing token was minted under."""
    return int(token) // EPOCH_STRIDE


class StoreReadOnlyError(RuntimeError):
    """The store refuses writes: it is a demoted standby (serving reads
    while it tails the primary's changelog). The API surfaces this as
    HTTP 503 with Retry-After — clients rotate to the next endpoint."""

    status = 503


class StoreDegradedError(StoreReadOnlyError):
    """The store flipped to read-only degraded mode after a full-disk
    write failure (SQLITE_FULL / ENOSPC) instead of crash-looping; a
    rate-limited recovery probe flips it back once writes succeed."""


class CompactedLogError(ValueError):
    """A changelog tail asked for rows at or below the compaction floor
    (pruned by ``snapshot_to``): the range no longer exists, and serving
    only the surviving rows would silently skip the pruned writes. The
    consumer must re-bootstrap from a snapshot."""

    def __init__(self, after_seq: int, floor: int):
        self.after_seq = after_seq
        self.floor = floor
        super().__init__(
            f"changelog rows after seq {after_seq} were compacted away "
            f"(floor: {floor}); re-bootstrap from a snapshot")


class StaleEpochError(ValueError):
    """A ``?since=`` feed token (or any epoch-qualified cursor) was
    minted under an OLDER store epoch — the primary it came from is gone
    and the consumer's incremental state may silently diverge from the
    promoted standby (replication lag at the moment of death). Surfaced
    as HTTP 410: the consumer must full-resync (the same
    ``cold_start_resync`` path an agent takeover uses)."""

    status = 410

    def __init__(self, token_epoch: int, current: int):
        self.token_epoch = token_epoch
        self.current = current
        super().__init__(
            f"feed token from store epoch {token_epoch} is stale "
            f"(current epoch: {current}); full resync required")


def _is_disk_full(exc: BaseException) -> bool:
    """SQLITE_FULL / ENOSPC signature — the one storage failure that is
    NOT transient weather and must flip degraded mode, not crash-loop."""
    if isinstance(exc, OSError) and getattr(exc, "errno", None) == errno.ENOSPC:
        return True
    return (isinstance(exc, sqlite3.OperationalError)
            and "disk is full" in str(exc))


def _now() -> str:
    # plx: allow(clock): persisted ISO row timestamps (created_at, lease renewed_at) are read cross-process and by humans — wall clock is the contract
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


class StoreBackend(abc.ABC):
    """The pluggable store contract (ISSUE 18): the verb surface every
    caller — API handlers, agents, replication, chaos wrappers — codes
    against. :class:`Store` is the single-SQLite implementation;
    :class:`~polyaxon_tpu.api.sharded_store.ShardedStore` routes the same
    surface over K of them. The abstract set below is the load-bearing
    core (feed, lifecycle, leases, listings); the full surface — run-
    scoped reads/writes, projects/tokens/quotas/clusters/config, serve
    verbs — is pinned by tests/test_sharded_store.py's surface-parity
    check rather than enumerated here, so the contract can't silently
    fork between implementations.

    Contract invariants every implementation must keep:

    - ``feed_token``/``parse_since`` round-trip, and a token minted
      before ANY failover (epoch change) raises :class:`StaleEpochError`;
    - ``get_changelog`` pages are strictly ``seq``-ascending, resumable
      from any returned seq, and raise :class:`CompactedLogError` below
      the compaction floor — never a silent gap;
    - write verbs honor ``fence=(lease_name, token)`` with
      :class:`StaleLeaseError` rejection;
    - ``transition_many``/``create_runs`` fire listeners only after
      their transaction commits, in order, for applied entries only.
    """

    @abc.abstractmethod
    def create_runs(self, project: str, runs: list, fence=None) -> list:
        ...

    @abc.abstractmethod
    def transition_many(self, transitions: list, fence=None) -> list:
        ...

    @abc.abstractmethod
    def list_runs(self, **kw: Any) -> list:
        ...

    @abc.abstractmethod
    def count_runs(self, **kw: Any) -> int:
        ...

    @abc.abstractmethod
    def get_changelog(self, after_seq: int = 0, limit: int = 500) -> list:
        ...

    @abc.abstractmethod
    def apply_changelog(self, rows: list) -> int:
        ...

    @abc.abstractmethod
    def changelog_span(self) -> dict:
        ...

    @abc.abstractmethod
    def current_seq(self) -> int:
        ...

    @abc.abstractmethod
    def current_epoch(self) -> int:
        ...

    @abc.abstractmethod
    def feed_token(self, seq: int) -> str:
        ...

    @abc.abstractmethod
    def parse_since(self, token) -> int:
        ...

    @abc.abstractmethod
    def since_token(self, run: dict) -> str:
        ...

    @abc.abstractmethod
    def acquire_lease(self, name: str, holder: str, *a: Any, **kw: Any):
        ...

    @abc.abstractmethod
    def promote(self) -> int:
        ...

    @abc.abstractmethod
    def snapshot(self, dirpath: str) -> dict:
        ...

    @abc.abstractmethod
    def add_transition_listener(self, fn) -> None:
        ...


class Store(StoreBackend):
    """Thread-safe SQLite store. One connection per thread (sqlite3
    check_same_thread), WAL so readers never block the writer."""

    def __init__(self, path: str = ":memory:", metrics=None,
                 replicate: bool = True, record_interval_s: float = 10.0):
        self.path = path
        self._local = threading.local()
        # serializes status transitions (read-check-insert-update must be
        # atomic across the agent/executor/API threads)
        self._transition_lock = threading.Lock()
        self._transition_listeners: list = []
        # cheap observability for scheduling-complexity tests and perf
        # triage: transactions opened + run rows deserialized. A dirty
        # scheduling pass must stay O(dirty) on both (tests/test_runtime_
        # agent.py asserts it), so the counters are part of the contract.
        self.stats = {"transactions": 0, "runs_deserialized": 0,
                      "fence_rejections": 0, "launch_intents": 0,
                      # write-ahead sweep suggestion windows (ISSUE 19)
                      "trial_intents": 0,
                      "epoch_fence_rejections": 0,
                      # data-plane self-healing counters (ISSUE 8):
                      # accumulated by DELTA from the cumulative counts
                      # pods report in their heartbeats
                      "train_anomalies_loss": 0, "train_anomalies_grad": 0,
                      "train_rollbacks": 0,
                      # serving traffic counters (ISSUE 9): same
                      # delta-from-cumulative contract, reported by serve
                      # pods in their heartbeats' `serve` payload
                      "serve_requests": 0, "serve_tokens": 0,
                      # request-path fault tolerance (ISSUE 12): shed
                      # admissions + KV-pressure preemptions bridge from
                      # the same payload; request retries are counted by
                      # the serve FRONT (wire count_serve_retries as its
                      # on_retry hook)
                      "serve_rejected": 0, "serve_preemptions": 0,
                      # serving raw speed (ISSUE 17): prefix-cache and
                      # speculative decoding counters, same delta contract
                      "serve_prefix_hits": 0, "serve_prefix_misses": 0,
                      "serve_cow_copies": 0,
                      "serve_spec_proposed": 0, "serve_spec_accepted": 0,
                      "serve_request_retries": 0,
                      # count_runs fast path (ISSUE 18 satellite): paged-
                      # listing bootstraps served from write-path row
                      # counters vs the COUNT(*) slow path, plus how many
                      # reconciles found (and repaired) drift
                      "count_fast": 0, "count_slow": 0,
                      "count_drift_repairs": 0,
                      # SLO alert state machine (ISSUE 20): one bump per
                      # PERSISTED transition (dedup'd upserts don't count),
                      # exported per target state — the chaos soak's
                      # exactly-once-across-takeover check reads these
                      "alert_transitions_pending": 0,
                      "alert_transitions_firing": 0,
                      "alert_transitions_resolved": 0}
        # per-project run-row counters behind the count_runs fast path:
        # lazily seeded from one GROUP BY, then maintained by the write
        # path (create_runs/delete_run) and INVALIDATED by replication
        # replay (apply_changelog upserts can't tell inserts from
        # updates). Every `count_reconcile_every` fast-path hits the
        # cache is re-derived from SQL and drift repaired loudly (the
        # stats counter) — the drift-reconciling slow path.
        self._run_counts: Optional[dict[str, int]] = None
        self._count_lock = threading.Lock()
        self._count_hits = 0
        self.count_reconcile_every = 1024
        # per-run (incarnation, last-seen cumulative train counters) for
        # delta accounting; in-memory like the counters themselves —
        # Prometheus counters are process-local by contract. Bounded by
        # live run rows: delete_run prunes its entry.
        self._train_seen: dict[str, tuple] = {}
        self._train_lock = threading.Lock()
        # serving traffic (ISSUE 9): last-reported gauges + counter
        # watermarks per (run, reporter incarnation) — each REPLICA of a
        # service run is its own reporter, so gauges SUM across fresh
        # incarnations and counters delta per incarnation (a replica
        # restart resets its cumulatives without double-counting). Gauges
        # age OUT of serve_traffic() after serve_fresh_s; the records
        # themselves are pruned at 10x that horizon (counter watermarks
        # must survive a beat gap) and on delete_run.
        self._serve_seen: dict[str, dict] = {}
        self.serve_fresh_s = 15.0
        # per-scrape aggregate cache: the three serve gauges would
        # otherwise each take _train_lock and walk every reporter record
        # per /metrics render, contending with the heartbeat hot path
        self._serve_scrape_cache: tuple = (float("-inf"), None)
        # store survivability (ISSUE 7): ``replicate`` keeps the
        # commit-ordered changelog every write appends to (a standby tails
        # it); ``_read_only`` is the demoted-standby write gate;
        # ``_degraded`` is the disk-full read-only mode with its
        # rate-limited recovery probe. Replication defaults ON for every
        # store — including short-lived CLI embedders — because a db file
        # with changelog GAPS is a trap: a server later opened on the same
        # .plx db would offer a tail that silently misses the gap's rows.
        # Growth is bounded by compaction (``snapshot_to`` /
        # ``ChangelogCompactor``; the server runs it via --compact-every),
        # and the floor it records turns any pruned-past tail into a loud
        # 410 instead of divergence. ``replicate=False`` is for stores
        # whose db will NEVER serve a tail (pure benchmarks).
        self._replicate = replicate
        self._read_only = False
        self._degraded: Optional[str] = None
        self._degraded_probe_at = 0.0
        self.degraded_probe_interval = 5.0
        self._disk_full_injected = 0  # chaos hook budget
        self._epoch = 0       # re-read from counters after schema init
        self._applied_seq = 0
        # observability (ISSUE 5): the store is the hub every component
        # already shares, so its registry is the process's one pane of
        # glass — the agent/reaper/reconciler register their series here
        # and `GET /metrics` renders it. Counters export the existing
        # ``stats`` dict via callbacks (no double bookkeeping). A shared
        # registry may be passed in (ISSUE 7: primary + standby export one
        # continuous pane across a failover).
        from ..obs.metrics import MetricsRegistry

        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # every Store sharing this registry contributes to ONE set of
        # families (counters SUM, epoch/degraded take the max/any view):
        # with last-writer-wins callbacks the primary's pre-failover
        # counts would vanish from the scrape the moment the standby
        # registered — the opposite of "one continuous pane"
        peers = getattr(self.metrics, "_store_sources", None)
        if peers is None:
            peers = []
            self.metrics._store_sources = peers
        peers.append(self)
        for stat, help_txt in (
            ("transactions", "Store transactions opened"),
            ("runs_deserialized", "Run rows deserialized from the store"),
            ("fence_rejections",
             "Fenced writes rejected for a stale lease token"),
            ("launch_intents", "Write-ahead launch intents recorded"),
            ("trial_intents",
             "Write-ahead sweep trial intents recorded (ISSUE 19)"),
            ("epoch_fence_rejections",
             "Fenced writes rejected because their token predates the "
             "store epoch (a write from before a failover)"),
        ):
            self.metrics.counter(
                f"polyaxon_store_{stat}_total", help_txt,
                value_fn=(lambda s=stat, p=peers:
                          sum(st.stats[s] for st in p)))
        # data-plane self-healing families (ISSUE 8; docs/OBSERVABILITY.md):
        # exported from the stats dict like every other store counter, so
        # the soak's strict scrape can reconcile them with its audit trail
        for kind in ("loss", "grad"):
            self.metrics.counter(
                "polyaxon_train_anomalies_total",
                "Non-finite training steps skipped by the divergence guard",
                labels={"kind": kind},
                value_fn=(lambda k=kind, p=peers: sum(
                    st.stats.get(f"train_anomalies_{k}", 0) for st in p)))
        self.metrics.counter(
            "polyaxon_train_rollbacks_total",
            "Divergence rollbacks to the latest complete checkpoint",
            value_fn=(lambda p=peers: sum(
                st.stats.get("train_rollbacks", 0) for st in p)))
        # serving traffic families (ISSUE 9; docs/OBSERVABILITY.md): the
        # control signal the agent's autoscaler consumes, exported from the
        # same heartbeat-fed state `serve_traffic()` reads — one source of
        # truth for the scrape and the scaler. Histograms observe the RAW
        # TTFT / inter-token samples pods drain into their beats (not a
        # lossy re-aggregation of pod-side percentiles).
        self.metrics.counter(
            "polyaxon_serve_requests_total",
            "Generate requests completed by serve pods",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_requests", 0) for st in p)))
        self.metrics.counter(
            "polyaxon_serve_generated_tokens_total",
            "Tokens generated by serve pods",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_tokens", 0) for st in p)))
        self.metrics.gauge(
            "polyaxon_serve_running_requests",
            "In-flight generate requests holding a decode slot (fresh "
            "reporters, all service runs)",
            value_fn=(lambda p=peers: float(sum(
                st._serve_traffic_for_scrape()["running"] for st in p))))
        self.metrics.gauge(
            "polyaxon_serve_waiting_requests",
            "Generate requests queued for admission (fresh reporters)",
            value_fn=(lambda p=peers: float(sum(
                st._serve_traffic_for_scrape()["waiting"] for st in p))))
        self.metrics.gauge(
            "polyaxon_serve_kv_block_utilization",
            "Reserved fraction of serve pods' KV cache blocks (fresh "
            "reporters, pooled)",
            value_fn=(lambda p=peers: max(
                (st._serve_traffic_for_scrape()["kv_utilization"]
                 for st in p), default=0.0)))
        self._h_serve_ttft = self.metrics.histogram(
            "polyaxon_serve_ttft_seconds",
            "Request arrival to first generated token (serve pods)")
        self._h_serve_itl = self.metrics.histogram(
            "polyaxon_serve_intertoken_seconds",
            "Interval between consecutive generated tokens (serve pods)")
        # request-path fault tolerance (ISSUE 12): overload shedding,
        # KV-pressure preemptions and replica drain state, bridged from
        # the same heartbeat payload; retries come from the serve front
        self.metrics.counter(
            "polyaxon_serve_rejected_total",
            "Generate requests shed at admission by serve pods (429)",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_rejected", 0) for st in p)))
        self.metrics.counter(
            "polyaxon_serve_preemptions_total",
            "Running sequences evicted back to waiting under KV pressure",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_preemptions", 0) for st in p)))
        self.metrics.counter(
            "polyaxon_serve_request_retries_total",
            "Generate requests retried against another replica by the "
            "serve front (connect failures / 503s)",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_request_retries", 0) for st in p)))
        self.metrics.gauge(
            "polyaxon_serve_draining",
            "Serve replicas currently draining (fresh reporters)",
            value_fn=(lambda p=peers: float(sum(
                st._serve_traffic_for_scrape()["draining"] for st in p))))
        # serving raw speed (ISSUE 17): prefix-shared paged KV and
        # speculative decoding, bridged from the same heartbeat payload —
        # counters through the incarnation-keyed delta path, the shared
        # blocks gauge from fresh reporters only
        self.metrics.counter(
            "polyaxon_serve_prefix_cache_hits_total",
            "Prompt KV blocks served from the shared prefix cache at "
            "admission (no re-prefill)",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_prefix_hits", 0) for st in p)))
        self.metrics.counter(
            "polyaxon_serve_prefix_cache_misses_total",
            "Prompt KV blocks prefilled fresh (not found in the prefix "
            "cache)",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_prefix_misses", 0) for st in p)))
        self.metrics.gauge(
            "polyaxon_serve_shared_kv_blocks",
            "KV blocks currently referenced by more than one sequence "
            "(fresh reporters, pooled)",
            value_fn=(lambda p=peers: float(sum(
                st._serve_traffic_for_scrape()["shared_kv_blocks"]
                for st in p))))
        self.metrics.counter(
            "polyaxon_serve_cow_copies_total",
            "Copy-on-write block copies triggered by writes into shared "
            "KV blocks",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_cow_copies", 0) for st in p)))
        self.metrics.counter(
            "polyaxon_serve_spec_tokens_proposed_total",
            "Draft tokens proposed by speculative decoding",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_spec_proposed", 0) for st in p)))
        self.metrics.counter(
            "polyaxon_serve_spec_tokens_accepted_total",
            "Draft tokens accepted by target verification",
            value_fn=(lambda p=peers: sum(
                st.stats.get("serve_spec_accepted", 0) for st in p)))
        self.metrics.gauge(
            "polyaxon_store_epoch",
            "Store epoch (bumped by every standby promotion)",
            value_fn=lambda p=peers: float(max(st._epoch for st in p)))
        self.metrics.gauge(
            "polyaxon_store_degraded",
            "1 while the store is in disk-full read-only degraded mode",
            value_fn=lambda p=peers: 1.0 if any(
                st._degraded is not None for st in p) else 0.0)
        self._h_write = self.metrics.histogram(
            "polyaxon_store_write_seconds",
            "Latency of lifecycle write transactions "
            "(transition batches, run creation)")
        self._h_sched = self.metrics.histogram(
            "polyaxon_schedule_latency_seconds",
            "Run creation to first running transition "
            "(the sched_bench time-to-running metric)")
        self._memory_conn: Optional[sqlite3.Connection] = None
        if path == ":memory:":
            # a single shared connection (serialized by a lock)
            self._memory_conn = sqlite3.connect(":memory:", check_same_thread=False)
            self._memory_conn.execute("PRAGMA busy_timeout=10000")
            self._memory_lock = threading.Lock()
        with self._conn_ctx() as conn:
            conn.executescript(_SCHEMA)
            # additive migration for pre-r5 databases (CREATE TABLE IF NOT
            # EXISTS won't grow an existing table)
            cols = {r[1] for r in conn.execute("PRAGMA table_info(runs)")}
            if "created_by" not in cols:
                conn.execute("ALTER TABLE runs ADD COLUMN created_by TEXT")
            if "tenant" not in cols:
                # tenancy (ISSUE 15): accounting unit, stamped at create;
                # pre-r15 rows read NULL and derive their tenant from
                # created_by at scheduling time
                conn.execute("ALTER TABLE runs ADD COLUMN tenant TEXT")
            if "heartbeat_at" not in cols:
                conn.execute("ALTER TABLE runs ADD COLUMN heartbeat_at TEXT")
            if "heartbeat_step" not in cols:
                # training-progress heartbeat fields (ISSUE 8): the step
                # the pod last reported, and when that VALUE last moved —
                # the stall-aware reaper and the dashboard's progress
                # column both read the age of the latter
                conn.execute(
                    "ALTER TABLE runs ADD COLUMN heartbeat_step INTEGER")
                conn.execute(
                    "ALTER TABLE runs ADD COLUMN heartbeat_step_at TEXT")
            if "change_seq" not in cols:
                # pre-r7: backfill in rowid (≈ insertion) order and point
                # the counter past the backfill
                conn.execute("ALTER TABLE runs ADD COLUMN change_seq INTEGER")
                conn.execute("UPDATE runs SET change_seq=rowid")
                conn.execute(
                    "UPDATE counters SET v=COALESCE("
                    "(SELECT MAX(change_seq) FROM runs), 0) "
                    "WHERE k='change_seq'")
            conn.execute("CREATE INDEX IF NOT EXISTS idx_runs_change_seq "
                         "ON runs (change_seq)")
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT v FROM counters WHERE k='store_epoch'").fetchone()
            self._epoch = int(row[0]) if row else 0
            row = conn.execute("SELECT MAX(seq) FROM changelog").fetchone()
            self._applied_seq = int(row[0]) if row and row[0] else 0
        # tenancy (ISSUE 15): in-memory quota view backing the
        # polyaxon_quota_chips{tenant} gauges — refreshed by every quota
        # verb and by get_quota_map() (the agent's 2s poll), so a scrape
        # never pays a table walk per series. The default-tenant series
        # registers from birth: the family is contracted in
        # EXPECTED_FAMILIES and must exist on an empty store too.
        self._quota_cache: dict[str, int] = {}
        self._quota_lock = threading.Lock()
        self._register_quota_gauge("default")
        for row_ in self.list_quotas():
            self._quota_cache[row_["tenant"]] = int(row_["chips"])
            self._register_quota_gauge(row_["tenant"])
        # federation (ISSUE 16): in-memory cluster-registry view backing the
        # polyaxon_cluster_{healthy,chips}{cluster} gauges — refreshed by
        # every cluster verb and by get_cluster_map() (the agents' poll), so
        # a scrape never pays a table walk per series. Like the quota
        # gauges, the families register from birth (a 'local' placeholder
        # series on a store with no registry): EXPECTED_FAMILIES contracts
        # them on an empty, non-federated store too.
        self._cluster_cache: dict[str, dict] = {}
        self._cluster_health: dict[str, bool] = {}
        self._cluster_lock = threading.Lock()
        self.metrics.counter(
            "polyaxon_cluster_spillovers_total",
            "Runs re-placed onto another cluster for capacity (spillover)")
        self.metrics.counter(
            "polyaxon_cluster_failovers_total",
            "Runs re-placed off a lost cluster onto survivors")
        self._register_cluster_gauges("local")
        for row_ in self.list_clusters():
            self._cluster_cache[row_["name"]] = row_
            self._cluster_health[row_["name"]] = bool(row_["healthy"])
            self._register_cluster_gauges(row_["name"])
        # SLO alerting (ISSUE 20): the firing gauge reads an in-memory
        # count maintained by the alert verbs (and re-derived by
        # changelog replay on a standby) — a scrape never pays a table
        # walk. Families register from birth like every contracted name.
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT COUNT(*) FROM alerts WHERE state='firing'"
            ).fetchone()
            self._alerts_firing = int(row[0]) if row else 0
        self.metrics.gauge(
            "polyaxon_alerts_firing",
            "Alerts currently in the firing state",
            value_fn=lambda p=peers: float(sum(
                getattr(st, "_alerts_firing", 0) for st in p)))
        for state_ in ("pending", "firing", "resolved"):
            self.metrics.counter(
                "polyaxon_alerts_transitions_total",
                "Persisted alert state-machine transitions "
                "(dedup'd same-state upserts do not count)",
                labels={"state": state_},
                value_fn=(lambda s_=state_, p=peers: sum(
                    st.stats.get(f"alert_transitions_{s_}", 0)
                    for st in p)))
        # metrics history (ISSUE 20): the registry's ring-buffer recorder
        # — one per registry (shared across failover peers, like the
        # families). Created idle: long-lived processes (server, agent)
        # start the sampler thread; unit-test stores stay thread-free.
        from ..obs.history import recorder_for

        self.record_interval_s = float(record_interval_s)
        self.recorder = recorder_for(
            self.metrics, interval_s=self.record_interval_s, start=False)

    # -- tenant quotas (ISSUE 15) ------------------------------------------

    def _register_quota_gauge(self, tenant: str) -> None:
        self.metrics.gauge(
            "polyaxon_quota_chips",
            "Configured per-tenant chip quota (0 = no quota row)",
            labels={"tenant": tenant},
            value_fn=lambda t=tenant: float(self._quota_cache.get(t, 0)))

    def set_quota(self, tenant: str, chips: int, fence=None) -> dict:
        """Upsert one tenant's chip quota (``PUT /api/v1/quotas/{tenant}``).
        Fenceable like every control-plane write: an embedder driving a
        write-fenced store passes its lease fence explicitly. Replicated
        — a promoted standby serves the same quota table."""
        chips = int(chips)
        if chips < 0:
            raise ValueError(f"quota chips must be >= 0, got {chips}")
        self._check_writable()
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            now = _now()
            conn.execute(
                "INSERT INTO quotas (tenant, chips, created_at, updated_at)"
                " VALUES (?,?,?,?) ON CONFLICT(tenant) DO UPDATE SET"
                " chips=excluded.chips, updated_at=excluded.updated_at",
                (tenant, chips, now, now))
            self._log_change(conn, "quota", {
                "tenant": tenant, "chips": chips, "created_at": now,
                "updated_at": now})
        with self._quota_lock:
            self._quota_cache[tenant] = chips
        self._register_quota_gauge(tenant)
        return {"tenant": tenant, "chips": chips}

    def get_quota(self, tenant: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT tenant, chips, created_at, updated_at FROM quotas "
                "WHERE tenant=?", (tenant,)).fetchone()
        if row is None:
            return None
        return {"tenant": row[0], "chips": row[1], "created_at": row[2],
                "updated_at": row[3]}

    def list_quotas(self) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT tenant, chips, created_at, updated_at FROM quotas "
                "ORDER BY tenant").fetchall()
        return [{"tenant": r[0], "chips": r[1], "created_at": r[2],
                 "updated_at": r[3]} for r in rows]

    def delete_quota(self, tenant: str, fence=None) -> bool:
        """Drop a tenant's quota row. In-flight runs of the deleted
        tenant fall back to the default quota LOUDLY (status condition +
        polyaxon_tenant_quota_fallbacks_total) — the scheduler never
        KeyErrors over a vanished tenant."""
        self._check_writable()
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            cur = conn.execute("DELETE FROM quotas WHERE tenant=?",
                               (tenant,))
            if cur.rowcount > 0:
                self._log_change(conn, "quota_delete", {"tenant": tenant})
        with self._quota_lock:
            self._quota_cache.pop(tenant, None)
        return cur.rowcount > 0

    def get_quota_map(self) -> dict[str, int]:
        """{tenant: chips} — ONE table read, refreshing the gauge cache.
        The agent polls this on its quota-refresh cadence; the gauges ride
        along for free."""
        with self._conn_ctx() as conn:
            rows = conn.execute("SELECT tenant, chips FROM quotas").fetchall()
        fresh = {r[0]: int(r[1]) for r in rows}
        with self._quota_lock:
            stale = set(self._quota_cache) - set(fresh)
            self._quota_cache.update(fresh)
            for t in stale:
                self._quota_cache.pop(t, None)
        for t in fresh:
            self._register_quota_gauge(t)
        return fresh

    # -- cluster registry (ISSUE 16) ---------------------------------------

    _CLUSTER_COLS = ("name", "region", "chip_type", "capacity",
                     "created_at", "updated_at")

    def _register_cluster_gauges(self, name: str) -> None:
        self.metrics.gauge(
            "polyaxon_cluster_healthy",
            "1 while the cluster's health lease is live "
            "(1 for the 'local' placeholder on a non-federated store)",
            labels={"cluster": name},
            value_fn=lambda n=name: (
                1.0 if self._cluster_health.get(n, True) else 0.0))
        self.metrics.gauge(
            "polyaxon_cluster_chips",
            "Registered chip capacity of the cluster (0 = unregistered)",
            labels={"cluster": name},
            value_fn=lambda n=name: float(
                (self._cluster_cache.get(n) or {}).get("capacity", 0)))

    def register_cluster(self, name: str, region: Optional[str] = None,
                         chip_type: Optional[str] = None, capacity: int = 0,
                         fence=None) -> dict:
        """Upsert one named cluster backend (``PUT /api/v1/clusters/{name}``
        and every federated agent's start()). Replicated like quotas — a
        promoted standby serves the same registry. Health is NOT written
        here: it is the cluster-health-<name> lease, so a dead writer's
        row can never claim liveness."""
        capacity = int(capacity)
        if capacity < 0:
            raise ValueError(f"cluster capacity must be >= 0, got {capacity}")
        self._check_writable()
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            now = _now()
            conn.execute(
                "INSERT INTO clusters (name, region, chip_type, capacity, "
                "created_at, updated_at) VALUES (?,?,?,?,?,?) "
                "ON CONFLICT(name) DO UPDATE SET region=excluded.region, "
                "chip_type=excluded.chip_type, capacity=excluded.capacity, "
                "updated_at=excluded.updated_at",
                (name, region, chip_type, capacity, now, now))
            self._log_change(conn, "cluster", {
                "name": name, "region": region, "chip_type": chip_type,
                "capacity": capacity, "created_at": now, "updated_at": now})
        row = {"name": name, "region": region, "chip_type": chip_type,
               "capacity": capacity}
        healthy = self._cluster_healthy(name)
        with self._cluster_lock:
            self._cluster_cache[name] = row
            self._cluster_health[name] = healthy
        self._register_cluster_gauges(name)
        return row

    def _cluster_healthy(self, name: str,
                         leases: Optional[dict] = None) -> bool:
        if leases is None:
            leases = {r["name"]: r for r in self.list_leases(
                prefix=CLUSTER_HEALTH_PREFIX)}
        row = leases.get(CLUSTER_HEALTH_PREFIX + name)
        return row is not None and not row["expired"]

    def get_cluster(self, name: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._CLUSTER_COLS)} FROM clusters "
                "WHERE name=?", (name,)).fetchone()
        if row is None:
            return None
        d = dict(zip(self._CLUSTER_COLS, row))
        d["healthy"] = self._cluster_healthy(name)
        with self._cluster_lock:
            self._cluster_health[name] = d["healthy"]
        return d

    def list_clusters(self) -> list[dict]:
        """Every registered cluster with its lease-derived ``healthy``
        flag — the registry view placement, spillover, and the dashboard
        read. Refreshes the gauge caches as a side effect (the agents'
        poll keeps the scrape view current)."""
        with self._conn_ctx() as conn:
            rows = conn.execute(
                f"SELECT {','.join(self._CLUSTER_COLS)} FROM clusters "
                "ORDER BY name").fetchall()
        leases = {r["name"]: r for r in self.list_leases(
            prefix=CLUSTER_HEALTH_PREFIX)}
        out = []
        for r in rows:
            d = dict(zip(self._CLUSTER_COLS, r))
            d["healthy"] = self._cluster_healthy(d["name"], leases)
            out.append(d)
        with self._cluster_lock:
            for d in out:
                self._cluster_cache[d["name"]] = d
                self._cluster_health[d["name"]] = d["healthy"]
        for d in out:
            self._register_cluster_gauges(d["name"])
        return out

    def delete_cluster(self, name: str, fence=None) -> bool:
        """Drop a cluster's registry row — the operator's explicit death
        certificate (``polyaxon clusters forget``). Runs still placed on
        the deleted cluster are re-placed UNCONDITIONALLY by the next
        federation pass: deleting asserts the pods are gone, which is why
        it is an operator verb and never automatic (see the split-brain
        note in docs/RESILIENCE.md)."""
        self._check_writable()
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            cur = conn.execute("DELETE FROM clusters WHERE name=?", (name,))
            if cur.rowcount > 0:
                self._log_change(conn, "cluster_delete", {"name": name})
        with self._cluster_lock:
            self._cluster_cache.pop(name, None)
            self._cluster_health.pop(name, None)
        return cur.rowcount > 0

    def get_cluster_map(self) -> dict[str, dict]:
        """{name: registry row + healthy} — the agents' poll (spill and
        placement decisions); the gauges ride along for free."""
        return {d["name"]: d for d in self.list_clusters()}

    def cluster_load(self) -> dict[str, int]:
        """{cluster: live non-terminal runs placed on it} — SQL-side, one
        GROUP BY. The spill walk's headroom estimate (floor one chip per
        run): a sibling whose live placed runs already cover its
        registered capacity is saturated, and spilling there would only
        relocate the queue."""
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT json_extract(meta, '$.cluster') AS c, COUNT(*) "
                "FROM runs WHERE status NOT IN "
                "('succeeded', 'failed', 'stopped', 'skipped') "
                "AND json_extract(meta, '$.cluster') IS NOT NULL "
                "GROUP BY c",
            ).fetchall()
        return {row[0]: int(row[1]) for row in rows}

    _PLACE_UNSET = object()

    def place_run(self, uuid: str, cluster: Optional[str],
                  expect: Any = _PLACE_UNSET, fence=None) -> bool:
        """CAS on a run's CURRENT placement (``meta.cluster``): atomically
        move it to ``cluster`` (None un-places it) iff its placement still
        equals ``expect``. This single verb is what makes federation
        duplicate-free: N agents may all try to claim an unplaced run
        (``expect=None``) or spill/fail-over a placed one — exactly one
        CAS wins, the rest observe False and drop it. Fires the change
        feed at the run's current status so the WINNING cluster's agent
        wakes immediately instead of waiting out its resync interval.
        Spill/failover hops append the previous placement to
        ``meta.placement_history`` (the anti-ping-pong record)."""
        self._check_writable()
        status = None
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                if not conn.in_transaction:
                    conn.execute("BEGIN IMMEDIATE")
                row = conn.execute(
                    "SELECT meta, status FROM runs WHERE uuid=?",
                    (uuid,)).fetchone()
                if row is None:
                    return False
                meta = json.loads(row[0]) if row[0] else {}
                current = meta.get("cluster")
                if expect is not self._PLACE_UNSET and current != expect:
                    return False
                if current == cluster:
                    return True  # idempotent re-place: no write, no wake
                if current is not None:
                    hist = list(meta.get("placement_history") or [])
                    hist.append(current)
                    from ..federation.placement import MAX_PLACEMENT_HISTORY

                    meta["placement_history"] = hist[-MAX_PLACEMENT_HISTORY:]
                if cluster is None:
                    meta.pop("cluster", None)
                else:
                    meta["cluster"] = cluster
                seq = self._bump_seq(conn)
                conn.execute(
                    "UPDATE runs SET meta=?, updated_at=?, change_seq=? "
                    "WHERE uuid=?",
                    (json.dumps(meta), _now(), seq, uuid))
                self._log_run_row(conn, uuid, seq=seq)
                status = row[1]
        self._notify_listeners([(uuid, status)])
        return True

    # -- connection plumbing ----------------------------------------------

    def _conn_ctx(self):
        store = self

        class _Ctx:
            def __enter__(self):
                store.stats["transactions"] += 1
                if store._disk_full_injected > 0:
                    # chaos hook (disk_full()): fail like SQLITE_FULL would,
                    # through the same detection path a real full disk hits
                    store._disk_full_injected -= 1
                    exc = sqlite3.OperationalError(
                        "database or disk is full (chaos: injected)")
                    store._mark_degraded(exc)
                    raise exc
                if store._memory_conn is not None:
                    store._memory_lock.acquire()
                    return store._memory_conn
                conn = getattr(store._local, "conn", None)
                if conn is None:
                    conn = sqlite3.connect(store.path, timeout=30)
                    conn.execute("PRAGMA journal_mode=WAL")
                    conn.execute("PRAGMA synchronous=NORMAL")
                    # don't fail instantly on a writer collision across
                    # processes (WAL allows one writer): wait it out
                    conn.execute("PRAGMA busy_timeout=10000")
                    store._local.conn = conn
                return conn

            @staticmethod
            def _commit(conn):
                try:
                    conn.commit()
                except BaseException as e:
                    # a full disk at COMMIT time flips degraded mode too —
                    # the commit is the fsync that actually needs the space
                    if _is_disk_full(e):
                        store._mark_degraded(e)
                    try:
                        conn.rollback()
                    except Exception:
                        pass
                    raise

            def __exit__(self, et, ev, tb):
                # rollback on error, ALWAYS: python sqlite3 leaves the
                # implicit transaction open otherwise — a half-applied
                # write would hold the writer lock and get silently flushed
                # by the next unrelated commit on this connection
                if ev is not None and _is_disk_full(ev):
                    # SQLITE_FULL from any statement in the body: degrade
                    # to read-only instead of crash-looping the API
                    store._mark_degraded(ev)
                if store._memory_conn is not None:
                    try:
                        if et is None:
                            self._commit(store._memory_conn)
                        else:
                            store._memory_conn.rollback()
                    finally:
                        store._memory_lock.release()
                else:
                    if et is None:
                        self._commit(store._local.conn)
                    else:
                        store._local.conn.rollback()

        return _Ctx()

    # -- degraded / read-only write gates (ISSUE 7) ------------------------

    def _mark_degraded(self, exc: BaseException) -> None:
        self._degraded = str(exc)
        self._degraded_probe_at = (time.monotonic()
                                   + self.degraded_probe_interval)

    def _check_writable(self) -> None:
        """Gate every mutating verb. Degraded (disk full) raises 503 after
        a rate-limited self-probe; a demoted standby raises 503 until
        promotion. Reads are never gated — degraded mode is read-ONLY, not
        down, and a standby serves reads by design."""
        if self._degraded is not None:
            if time.monotonic() >= self._degraded_probe_at:
                self.probe_recovery()
            if self._degraded is not None:
                raise StoreDegradedError(
                    f"store is degraded (read-only): {self._degraded}")
        if self._read_only:
            raise StoreReadOnlyError(
                "store is a demoted standby (read-only); writes resume "
                "after promotion")

    def probe_recovery(self) -> bool:
        """One recovery probe out of disk-full degraded mode: attempt a
        tiny real write; success clears the flag (space was freed), failure
        re-arms the probe timer. Called automatically (rate-limited) by the
        write gate, and callable by operators/tests directly."""
        self._degraded_probe_at = (time.monotonic()
                                   + self.degraded_probe_interval)
        try:
            with self._conn_ctx() as conn:
                conn.execute(
                    "INSERT OR REPLACE INTO control_config (key, value) "
                    "VALUES ('_degraded_probe', ?)", (_now(),))
        except Exception:
            return False
        self._degraded = None
        return True

    def chaos_disk_full(self, n: int = 1) -> None:
        """Chaos hook (``disk_full()`` in the soak harness): the next ``n``
        transactions fail with the SQLITE_FULL signature, exercising the
        degraded-mode flip end to end."""
        self._disk_full_injected += int(n)

    def set_read_only(self, flag: bool) -> None:
        """Demote (True: standby mode — writes 503, reads serve) or lift.
        :meth:`promote` lifts it too, atomically with the epoch bump."""
        self._read_only = bool(flag)

    @property
    def read_only(self) -> bool:
        return self._read_only

    @property
    def degraded(self) -> Optional[str]:
        """The degradation reason while in disk-full read-only mode."""
        return self._degraded

    # -- projects ----------------------------------------------------------

    def create_project(self, name: str, description: Optional[str] = None) -> dict:
        self._check_writable()
        with self._conn_ctx() as conn:
            now = _now()
            cur = conn.execute(
                "INSERT OR IGNORE INTO projects (name, description, created_at) VALUES (?,?,?)",
                (name, description, now),
            )
            if cur.rowcount > 0:
                self._log_change(conn, "project", {
                    "name": name, "description": description,
                    "created_at": now})
        return self.get_project(name)

    def get_project(self, name: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT name, description, created_at FROM projects WHERE name=?", (name,)
            ).fetchone()
        if not row:
            return None
        return {"name": row[0], "description": row[1], "created_at": row[2]}

    def list_projects(self) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT name, description, created_at FROM projects ORDER BY name"
            ).fetchall()
        return [{"name": r[0], "description": r[1], "created_at": r[2]} for r in rows]

    # -- tokens (RBAC-lite, SURVEY.md:104) ----------------------------------

    @staticmethod
    def _token_hash(raw: str) -> str:
        import hashlib

        return hashlib.sha256(raw.encode()).hexdigest()

    def create_token(self, project: Optional[str] = None,
                     label: Optional[str] = None) -> dict:
        """Mint an access token. ``project=None`` = admin (all projects);
        otherwise scoped to that one project. Only the sha256 lands in the
        DB — the raw token is returned once and never recoverable."""
        import secrets

        raw = secrets.token_hex(24)
        self._check_writable()
        with self._conn_ctx() as conn:
            now = _now()
            cur = conn.execute(
                "INSERT INTO tokens (token_hash, project, label, created_at) "
                "VALUES (?,?,?,?)",
                (self._token_hash(raw), project, label, now),
            )
            tid = cur.lastrowid
            # only the hash replicates — the raw token never lands in the
            # changelog any more than it lands in the primary's table
            self._log_change(conn, "token", {
                "id": tid, "token_hash": self._token_hash(raw),
                "project": project, "label": label, "created_at": now})
        return {"id": tid, "token": raw, "project": project, "label": label}

    def resolve_token(self, raw: str) -> Optional[dict]:
        """{'id', 'project', 'label'} for a live token (project None =
        admin), or None for unknown/revoked."""
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT id, project, label FROM tokens "
                "WHERE token_hash=? AND revoked=0",
                (self._token_hash(raw),),
            ).fetchone()
        return ({"id": row[0], "project": row[1], "label": row[2]}
                if row else None)

    def list_tokens(self) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT id, project, label, created_at, revoked FROM tokens "
                "ORDER BY id"
            ).fetchall()
        return [{"id": r[0], "project": r[1], "label": r[2],
                 "created_at": r[3], "revoked": bool(r[4])} for r in rows]

    def revoke_token(self, token_id: int) -> bool:
        self._check_writable()
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "UPDATE tokens SET revoked=1 WHERE id=?", (token_id,))
            if cur.rowcount > 0:
                self._log_change(conn, "token_revoke", {"id": token_id})
            return cur.rowcount > 0

    def has_tokens(self) -> bool:
        """Any token row, revoked or not: once a server has ever minted a
        token, auth stays engaged across restarts — revoking the last token
        must lock the server down, not silently reopen it.

        Break-glass recovery (ADVICE r4): the lockdown has no *network*
        escape hatch by design, but an operator with shell access to the
        server host can always recover — start the server with
        ``--auth-token <secret>`` (the static admin token bypasses the
        store) and mint a fresh scoped token via ``POST /api/v1/tokens``,
        or delete rows from the ``tokens`` table in the store's sqlite db.
        Documented in README "Auth"."""
        with self._conn_ctx() as conn:
            return conn.execute(
                "SELECT 1 FROM tokens LIMIT 1").fetchone() is not None

    # -- agent leases + fencing (control-plane crash safety) ---------------

    _LEASE_COLS = ("name", "holder", "token", "ttl", "acquired_at",
                   "renewed_at")

    @staticmethod
    def _lease_age(renewed_at: str) -> float:
        t = datetime.datetime.fromisoformat(renewed_at)
        if t.tzinfo is None:
            t = t.replace(tzinfo=datetime.timezone.utc)
        # plx: allow(clock): renewed_at is a PERSISTED wall timestamp (file DBs survive restarts, leases span processes) — monotonic cannot compare across processes; the TTL grace absorbs NTP slew
        return (datetime.datetime.now(datetime.timezone.utc)
                - t).total_seconds()

    def _lease_row(self, conn, name: str) -> Optional[dict]:
        row = conn.execute(
            f"SELECT {','.join(self._LEASE_COLS)} FROM agent_leases "
            "WHERE name=?", (name,)).fetchone()
        return dict(zip(self._LEASE_COLS, row)) if row else None

    def acquire_lease(self, name: str, holder: str,
                      ttl: float = 30.0) -> Optional[dict]:
        """Take the named lease if it is free, expired (no renewal within
        its TTL), or already ours. Every successful acquisition bumps the
        monotonic fencing token — including self-reacquisition, so a
        holder that lost track of time gets a NEW token and its old one
        dies. Returns the lease dict, or None while another holder's
        lease is live.

        Tokens are epoch-strided (``epoch * EPOCH_STRIDE + counter``):
        a promoted standby mints tokens strictly greater than — and never
        colliding with — anything the dead primary handed out, including
        acquisitions its changelog never replicated."""
        self._check_writable()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                # liveness check and token bump must be ONE unit across
                # processes too (the SELECT alone runs in autocommit on a
                # file DB): two double-started agents must never both
                # conclude "expired" and both believe they acquired
                if not conn.in_transaction:
                    conn.execute("BEGIN IMMEDIATE")
                row = self._lease_row(conn, name)
                if (row is not None and row["holder"] != holder
                        and self._lease_age(row["renewed_at"]) < row["ttl"]):
                    return None
                key = f"lease_token:{name}"
                conn.execute(
                    "INSERT OR IGNORE INTO counters (k, v) VALUES (?, 0)",
                    (key,))
                conn.execute("UPDATE counters SET v=v+1 WHERE k=?", (key,))
                token = conn.execute(
                    "SELECT v FROM counters WHERE k=?", (key,)).fetchone()[0]
                token += self._epoch * EPOCH_STRIDE
                now = _now()
                conn.execute(
                    "INSERT OR REPLACE INTO agent_leases "
                    "(name, holder, token, ttl, acquired_at, renewed_at) "
                    "VALUES (?,?,?,?,?,?)",
                    (name, holder, token, float(ttl), now, now))
                return self._lease_row(conn, name)

    def renew_lease(self, name: str, holder: str, token: int) -> bool:
        """Stamp renewed_at iff (holder, token) still own the lease.
        False means a newer acquisition exists (or the lease was
        released): the caller is stale and must demote itself."""
        return self.renew_leases([(name, token)], holder)[0]

    def renew_leases(self, renewals: list[tuple], holder: str) -> list[bool]:
        """Batch renewal: one transaction for every lease this holder
        keeps alive (a sharded agent renews all its shard leases + its
        presence row per heartbeat instead of K round-trips). Each entry
        is ``(name, token)``; returns per-entry success — False means
        that lease has a newer acquisition (or was released) and the
        holder must demote itself FOR THAT SHARD ONLY."""
        self._check_writable()
        out: list[bool] = []
        with self._conn_ctx() as conn:
            now = _now()
            for name, token in renewals:
                cur = conn.execute(
                    "UPDATE agent_leases SET renewed_at=? "
                    "WHERE name=? AND holder=? AND token=?",
                    (now, name, holder, token))
                out.append(cur.rowcount > 0)
        return out

    def release_lease(self, name: str, holder: str, token: int) -> bool:
        """Explicit release on graceful shutdown — a successor acquires
        instantly instead of waiting out the TTL. Only the current
        (holder, token) may release; the token counter survives, so the
        next acquisition still gets a strictly newer token."""
        self._check_writable()
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "DELETE FROM agent_leases "
                "WHERE name=? AND holder=? AND token=?",
                (name, holder, token))
        return cur.rowcount > 0

    def get_lease(self, name: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = self._lease_row(conn, name)
        if row is not None:
            row["expired"] = self._lease_age(row["renewed_at"]) >= row["ttl"]
        return row

    def claim_config(self, key: str, value: str) -> str:
        """First-writer-wins fleet setting: atomically record ``value``
        for ``key`` unless some agent already did, and return the WINNING
        value — every later claimant must conform to it. Backs the
        num_shards agreement check (a fleet hashing the run space with
        two different K values double-owns runs under valid fences)."""
        self._check_writable()
        with self._conn_ctx() as conn:
            cur = conn.execute(
                "INSERT OR IGNORE INTO control_config (key, value) "
                "VALUES (?, ?)", (key, str(value)))
            row = conn.execute(
                "SELECT value FROM control_config WHERE key=?",
                (key,)).fetchone()
            if cur.rowcount > 0:
                # only the WINNING claim replicates: the fleet's agreed
                # value must survive a failover
                self._log_change(conn, "config",
                                 {"key": key, "value": row[0]})
        return row[0]

    def get_config(self, key: str) -> Optional[str]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                "SELECT value FROM control_config WHERE key=?",
                (key,)).fetchone()
        return row[0] if row else None

    def set_config(self, key: str, value: str) -> None:
        """Operator override of a pinned fleet setting (e.g. resizing the
        shard partition): stop the WHOLE fleet first — agents adopt the
        pinned value only at start(), and a mixed fleet double-owns runs."""
        self._check_writable()
        with self._conn_ctx() as conn:
            conn.execute(
                "INSERT OR REPLACE INTO control_config (key, value) "
                "VALUES (?, ?)", (key, str(value)))
            self._log_change(conn, "config", {"key": key, "value": str(value)})

    def list_leases(self, prefix: Optional[str] = None) -> list[dict]:
        """Every lease row (optionally name-prefixed: ``shard-`` for the
        work partition, ``agent-`` for live-agent presence), each with its
        ``expired`` flag — the input to shard fair-share balancing and the
        per-agent ownership table in ``/api/v1/stats``."""
        q = (f"SELECT {','.join(self._LEASE_COLS)} FROM agent_leases")
        args: list = []
        if prefix:
            q += " WHERE name LIKE ?"
            args.append(prefix.replace("%", "") + "%")
        q += " ORDER BY name"
        with self._conn_ctx() as conn:
            rows = conn.execute(q, args).fetchall()
        out = []
        for r in rows:
            d = dict(zip(self._LEASE_COLS, r))
            d["expired"] = self._lease_age(d["renewed_at"]) >= d["ttl"]
            out.append(d)
        return out

    def _check_fence(self, conn, fence) -> None:
        """Reject a fenced write whose token is no longer current. Atomic
        with the write it guards: python sqlite3 only opens the implicit
        transaction on DML — a bare SELECT runs in autocommit, which on a
        file DB shared by two processes would let a takeover commit
        BETWEEN this read and our write. BEGIN IMMEDIATE grabs the writer
        lock first, so the token read and the guarded write commit as one
        unit — there is no window where a stale agent's batch lands after
        a newer acquisition."""
        if fence is None:
            return
        if not conn.in_transaction:
            conn.execute("BEGIN IMMEDIATE")
        name, token = fence
        row = conn.execute(
            "SELECT token FROM agent_leases WHERE name=?", (name,)).fetchone()
        current = row[0] if row else None
        if current != token:
            self.stats["fence_rejections"] += 1
            if (token is not None and token >= 0
                    and token_epoch(token) < self._epoch):
                # a real minted token from an OLDER store epoch: a write
                # from before a failover — the class of rejection the
                # store-outage soak asserts happened at least once.
                # (token >= 0 excludes the agents' poison fences, whose
                # sentinel -1 was never minted by any epoch.)
                self.stats["epoch_fence_rejections"] += 1
            # per-lease rejection family (lazy get-or-create): the sharded
            # soak asserts that a specific SHARD's stale owner was fenced,
            # not just that some rejection happened somewhere
            self.metrics.counter(
                "polyaxon_store_fence_rejections_by_lease_total",
                "Fenced writes rejected for a stale token, by lease name",
                labels={"lease": name}).inc()
            raise StaleLeaseError(name, token, current)

    # -- launch intents (write-ahead pod creation) -------------------------

    def record_launch_intent(self, run_uuid: str, lease_holder: Optional[str],
                             token: Optional[int],
                             lease_name: Optional[str] = None,
                             fence=None) -> dict:
        """Write-ahead row for a pod launch: bump the attempt counter, set
        state='intent', and stamp ``meta.owner = {lease_id, token,
        attempt}`` on the run — all in ONE transaction, BEFORE any cluster
        call. A crash after this commit but before the pods exist leaves
        state='intent' with no pods: the successor relaunches. A crash
        after :meth:`mark_launched` leaves state='launched': the successor
        adopts the live pods instead of creating a second set."""
        self._check_writable()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                prev = conn.execute(
                    "SELECT attempt FROM launch_intents WHERE run_uuid=?",
                    (run_uuid,)).fetchone()
                attempt = (prev[0] if prev else 0) + 1
                now = _now()
                conn.execute(
                    "INSERT OR REPLACE INTO launch_intents (run_uuid, "
                    "lease_name, lease_holder, token, attempt, state, "
                    "created_at, updated_at) VALUES (?,?,?,?,?,?,?,?)",
                    (run_uuid, lease_name, lease_holder, token, attempt,
                     "intent", now, now))
                self._log_change(conn, "intent", {
                    "run_uuid": run_uuid, "lease_name": lease_name,
                    "lease_holder": lease_holder, "token": token,
                    "attempt": attempt, "state": "intent",
                    "created_at": now, "updated_at": now})
                self._stamp_owner(conn, run_uuid, lease_holder, token, attempt)
                self.stats["launch_intents"] += 1
        return {"run_uuid": run_uuid, "attempt": attempt, "state": "intent",
                "lease_holder": lease_holder, "token": token}

    def mark_launched(self, run_uuid: str, fence=None) -> None:
        """Flip the intent to state='launched' AFTER the cluster accepted
        every manifest — the pods exist now; a successor must adopt."""
        self._check_writable()
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            conn.execute(
                "UPDATE launch_intents SET state='launched', updated_at=? "
                "WHERE run_uuid=?", (_now(), run_uuid))
            self._log_intent_row(conn, run_uuid)

    def adopt_launch(self, run_uuid: str, lease_holder: Optional[str],
                     token: Optional[int], fence=None) -> None:
        """Re-own a live pod set after an agent restart: update the intent
        row and meta.owner to the NEW lease without bumping the attempt
        counter — adoption is not a launch."""
        self._check_writable()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                now = _now()
                row = conn.execute(
                    "SELECT attempt FROM launch_intents WHERE run_uuid=?",
                    (run_uuid,)).fetchone()
                attempt = row[0] if row else 1
                conn.execute(
                    "INSERT OR REPLACE INTO launch_intents (run_uuid, "
                    "lease_name, lease_holder, token, attempt, state, "
                    "created_at, updated_at) VALUES (?,?,?,?,?,'launched',?,?)",
                    (run_uuid, None, lease_holder, token, attempt, now, now))
                self._log_change(conn, "intent", {
                    "run_uuid": run_uuid, "lease_name": None,
                    "lease_holder": lease_holder, "token": token,
                    "attempt": attempt, "state": "launched",
                    "created_at": now, "updated_at": now})
                self._stamp_owner(conn, run_uuid, lease_holder, token, attempt)

    def _log_intent_row(self, conn, run_uuid: str) -> None:
        """Replicate the launch-intent row as it now stands."""
        if not self._replicate:
            return
        cols = ("run_uuid", "lease_name", "lease_holder", "token",
                "attempt", "state", "created_at", "updated_at")
        row = conn.execute(
            f"SELECT {','.join(cols)} FROM launch_intents WHERE run_uuid=?",
            (run_uuid,)).fetchone()
        if row is not None:
            self._log_change(conn, "intent", dict(zip(cols, row)))

    def get_launch_intent(self, run_uuid: str) -> Optional[dict]:
        cols = ("run_uuid", "lease_name", "lease_holder", "token", "attempt",
                "state", "created_at", "updated_at")
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(cols)} FROM launch_intents "
                "WHERE run_uuid=?", (run_uuid,)).fetchone()
        return dict(zip(cols, row)) if row else None

    def _stamp_owner(self, conn, run_uuid: str, lease_holder, token,
                     attempt: int) -> None:
        row = conn.execute(
            "SELECT meta FROM runs WHERE uuid=?", (run_uuid,)).fetchone()
        if row is None:
            return
        meta = json.loads(row[0]) if row[0] else {}
        meta["owner"] = {"lease_id": lease_holder, "token": token,
                         "attempt": attempt}
        seq = self._bump_seq(conn)
        conn.execute(
            "UPDATE runs SET meta=?, updated_at=?, change_seq=? WHERE uuid=?",
            (json.dumps(meta), _now(), seq, run_uuid))
        self._log_run_row(conn, run_uuid, seq=seq)

    # -- trial intents (write-ahead sweep windows, ISSUE 19) ---------------

    _TRIAL_INTENT_COLS = ("sweep_uuid", "trial_index", "params_hash",
                          "suggestion", "run_uuid", "state", "created_at",
                          "updated_at")

    def record_trial_intents(self, sweep_uuid: str, entries: list,
                             fence=None) -> list[dict]:
        """Write-ahead rows for one suggestion window: commit every
        (trial_index, params_hash) of the window in ONE transaction BEFORE
        ``create_runs``. A crash after this commit but before the children
        exist leaves state='intent' rows with no matching child: the
        successor re-derives the same suggestions (the sampler is seeded
        per (sweep_uuid, trial_index)) and launches them exactly once. A
        replayed window whose re-derived hash disagrees with the recorded
        one raises — a silent divergence here is a duplicated trial with a
        different identity, the exact bug the intent exists to prevent."""
        self._check_writable()
        out: list[dict] = []
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                now = _now()
                for e in entries:
                    idx = int(e["trial_index"])
                    phash = e.get("params_hash")
                    sugg = e.get("suggestion")
                    if sugg is not None and not isinstance(sugg, str):
                        sugg = json.dumps(sugg, sort_keys=True)
                    prev = conn.execute(
                        "SELECT params_hash, suggestion, run_uuid, state, "
                        "created_at FROM trial_intents WHERE sweep_uuid=? "
                        "AND trial_index=?", (sweep_uuid, idx)).fetchone()
                    if prev is not None:
                        if phash and prev[0] and phash != prev[0]:
                            raise RuntimeError(
                                f"trial intent replay mismatch for sweep "
                                f"{sweep_uuid} trial {idx}: recorded hash "
                                f"{prev[0]} != re-derived {phash}")
                        out.append({"sweep_uuid": sweep_uuid,
                                    "trial_index": idx,
                                    "params_hash": prev[0],
                                    "suggestion": prev[1],
                                    "run_uuid": prev[2], "state": prev[3],
                                    "created_at": prev[4], "updated_at": now})
                        continue
                    conn.execute(
                        "INSERT INTO trial_intents (sweep_uuid, trial_index, "
                        "params_hash, suggestion, run_uuid, state, "
                        "created_at, updated_at) "
                        "VALUES (?,?,?,?,NULL,'intent',?,?)",
                        (sweep_uuid, idx, phash, sugg, now, now))
                    row = {"sweep_uuid": sweep_uuid, "trial_index": idx,
                           "params_hash": phash, "suggestion": sugg,
                           "run_uuid": None, "state": "intent",
                           "created_at": now, "updated_at": now}
                    self._log_change(conn, "trial_intent", row)
                    self.stats["trial_intents"] += 1
                    out.append(row)
        return out

    def mark_trials_created(self, sweep_uuid: str, entries: list,
                            fence=None) -> None:
        """Flip window intents to state='created' AFTER ``create_runs``
        committed the child rows — the trials exist now; a successor must
        adopt them by (sweep_uuid, trial_index), never re-create. Entries
        are ``(trial_index, run_uuid)`` pairs."""
        self._check_writable()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                now = _now()
                for idx, run_uuid in entries:
                    conn.execute(
                        "UPDATE trial_intents SET state='created', "
                        "run_uuid=?, updated_at=? WHERE sweep_uuid=? AND "
                        "trial_index=?", (run_uuid, now, sweep_uuid,
                                          int(idx)))
                    if self._replicate:
                        row = conn.execute(
                            f"SELECT {','.join(self._TRIAL_INTENT_COLS)} "
                            "FROM trial_intents WHERE sweep_uuid=? AND "
                            "trial_index=?",
                            (sweep_uuid, int(idx))).fetchone()
                        if row is not None:
                            self._log_change(
                                conn, "trial_intent",
                                dict(zip(self._TRIAL_INTENT_COLS, row)))

    def list_trial_intents(self, sweep_uuid: str) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                f"SELECT {','.join(self._TRIAL_INTENT_COLS)} FROM "
                "trial_intents WHERE sweep_uuid=? ORDER BY trial_index",
                (sweep_uuid,)).fetchall()
        return [dict(zip(self._TRIAL_INTENT_COLS, r)) for r in rows]

    # -- runs --------------------------------------------------------------

    _RUN_COLS = (
        "uuid", "project", "name", "kind", "status", "spec", "compiled",
        "inputs", "outputs", "meta", "tags", "original_uuid", "cloning_kind",
        "pipeline_uuid", "created_by", "tenant", "created_at", "updated_at",
        "started_at", "finished_at", "heartbeat_at", "heartbeat_step",
        "heartbeat_step_at", "change_seq",
    )
    _JSON_COLS = {"spec", "compiled", "inputs", "outputs", "meta", "tags"}

    def _bump_seq(self, conn, n: int = 1) -> int:
        """Advance the change counter by ``n`` inside the CURRENT write
        transaction and return the new top value. The UPDATE acquires
        SQLite's single-writer lock, so assigned seqs are strictly ordered
        with commit order — the property ?since= needs to never lose a
        row (a wall-clock stamp can predate a competing commit)."""
        conn.execute("UPDATE counters SET v=v+? WHERE k='change_seq'", (n,))
        return conn.execute(
            "SELECT v FROM counters WHERE k='change_seq'").fetchone()[0]

    def current_seq(self) -> int:
        """Latest committed change_seq (snapshot-consistent bootstrap token
        for incremental fetches: an in-flight writer's bump is invisible
        until its commit, so its rows always land AFTER this value)."""
        with self._conn_ctx() as conn:
            return conn.execute(
                "SELECT v FROM counters WHERE k='change_seq'").fetchone()[0]

    # -- epoch + feed tokens (ISSUE 7) -------------------------------------

    def current_epoch(self) -> int:
        """The store epoch: 0 at birth, bumped by every :meth:`promote`.
        Cached in memory — promotion happens in the owning process."""
        return self._epoch

    def feed_token(self, seq: int) -> str:
        """Epoch-qualified ``?since=`` token. Epoch 0 emits the bare seq
        (byte-compatible with pre-failover deployments); a promoted store
        emits ``"<epoch>:<seq>"`` so a consumer's pre-failover cursor is
        deterministically rejected (410) instead of silently diverging."""
        return f"{self._epoch}:{seq}" if self._epoch else str(seq)

    def parse_since(self, token) -> int:
        """Validate a feed token against the CURRENT epoch and return its
        seq. Bare ints (internal callers, legacy tokens) are epoch 0.
        Raises :class:`StaleEpochError` when the token's epoch is not this
        store's — the consumer's incremental state may have diverged by
        exactly the replication lag at failover, so the only safe answer
        is a full resync."""
        if isinstance(token, int):
            return token
        s = str(token)
        if ":" in s:
            e_str, _, seq_str = s.partition(":")
            epoch, seq = int(e_str), int(seq_str)
        else:
            epoch, seq = 0, int(s)
        if epoch != self._epoch:
            raise StaleEpochError(epoch, self._epoch)
        return seq

    # -- changelog (replication log; ISSUE 7 tentpole (a)) -----------------

    def _log_change(self, conn, op: str, payload: dict,
                    seq: Optional[int] = None) -> Optional[int]:
        """Append one replicated delta INSIDE the current write
        transaction. ``seq`` reuses the row's already-bumped change_seq
        (run upserts); None draws a fresh one — either way the seq was
        assigned under the writer lock, so changelog order is commit
        order."""
        if not self._replicate:
            return seq
        if seq is None:
            seq = self._bump_seq(conn)
        conn.execute(
            "INSERT OR REPLACE INTO changelog "
            "(seq, epoch, op, payload, created_at) VALUES (?,?,?,?,?)",
            (seq, self._epoch, op, json.dumps(payload), _now()))
        return seq

    def _raw_run_row(self, conn, uuid: str) -> Optional[dict]:
        """The run row with JSON columns as their stored TEXT — the
        changelog payload shape (replay re-inserts verbatim; no
        deserialize/reserialize drift, and no runs_deserialized count)."""
        row = conn.execute(
            f"SELECT {','.join(self._RUN_COLS)} FROM runs WHERE uuid=?",
            (uuid,)).fetchone()
        return dict(zip(self._RUN_COLS, row)) if row else None

    def _log_run_row(self, conn, uuid: str,
                     seq: Optional[int] = None) -> None:
        if not self._replicate:
            return
        row = self._raw_run_row(conn, uuid)
        if row is not None:
            self._log_change(conn, "run", {"row": row}, seq=seq)

    def get_changelog(self, after_seq: int = 0,
                      limit: int = 500) -> list[dict]:
        """Changelog rows strictly after ``after_seq``, seq-ascending —
        what a warm standby tails (in-process or via GET
        /api/v1/changelog). A cursor below the compaction floor raises
        :class:`CompactedLogError`: the pruned rows are gone, and
        silently serving only the survivors would diverge the tailer."""
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT seq, epoch, op, payload, created_at FROM changelog "
                "WHERE seq>? ORDER BY seq LIMIT ?",
                (int(after_seq), int(limit))).fetchall()
            # floor check AFTER the rows read: on a file DB both SELECTs
            # run in autocommit, so a concurrent compaction could prune
            # BETWEEN a floor-first check and the rows read — handing a
            # lagging tailer post-gap rows with no error. Checking the
            # (monotonic) floor afterwards closes that window: if the
            # cursor is below the floor now, the rows may straddle a
            # prune and must not be served.
            row = conn.execute(
                "SELECT v FROM counters WHERE k='changelog_floor'"
            ).fetchone()
            floor = int(row[0]) if row else 0
            if int(after_seq) < floor:
                raise CompactedLogError(int(after_seq), floor)
        return [{"seq": r[0], "epoch": r[1], "op": r[2],
                 "payload": json.loads(r[3]), "created_at": r[4]}
                for r in rows]

    def changelog_span(self) -> dict:
        """{'seq': newest changelog seq, 'epoch': current epoch} — the
        replication-lag numerator a standby compares its applied seq to."""
        with self._conn_ctx() as conn:
            row = conn.execute("SELECT MAX(seq) FROM changelog").fetchone()
        return {"seq": int(row[0]) if row and row[0] else 0,
                "epoch": self._epoch}

    def apply_changelog(self, rows: list[dict]) -> int:
        """Replay replicated changelog rows (standby tail). Idempotent:
        rows at or below the applied watermark are skipped, so a re-poll
        after a partial failure never double-applies. Bypasses the
        read-only gate by design (replication IS the standby's write path)
        and fires no transition listeners — a standby is passive until
        promotion, after which agents full-resync anyway."""
        todo = sorted((r for r in rows if r["seq"] > self._applied_seq),
                      key=lambda r: r["seq"])
        if not todo:
            return 0
        with self._transition_lock:
            with self._conn_ctx() as conn:
                max_epoch = self._epoch
                for rec in todo:
                    self._apply_change(conn, rec)
                    conn.execute(
                        "INSERT OR REPLACE INTO changelog "
                        "(seq, epoch, op, payload, created_at) "
                        "VALUES (?,?,?,?,?)",
                        (rec["seq"], rec["epoch"], rec["op"],
                         json.dumps(rec["payload"]), rec["created_at"]))
                    max_epoch = max(max_epoch, int(rec["epoch"]))
                # todo is seq-sorted, so the last element IS the
                # watermark — taking it from an unsorted input would
                # leave _applied_seq below applied rows and re-apply them
                # (duplicating plain-INSERT ops) on the next poll
                last = todo[-1]["seq"]
                conn.execute(
                    "UPDATE counters SET v=MAX(v, ?) WHERE k='change_seq'",
                    (last,))
                if max_epoch != self._epoch:
                    conn.execute(
                        "UPDATE counters SET v=? WHERE k='store_epoch'",
                        (max_epoch,))
                    self._epoch = max_epoch
                self._applied_seq = last
        if any(r["op"] in ("run", "delete_run") for r in todo):
            # replayed upserts can't tell an insert from an update — the
            # row counters re-derive on the next fast-path count
            self._count_invalidate()
        return len(todo)

    def _apply_change(self, conn, rec: dict) -> None:
        op, p = rec["op"], rec["payload"]
        if op == "run":
            row = p["row"]
            conn.execute(
                f"INSERT OR REPLACE INTO runs ({','.join(self._RUN_COLS)}) "
                f"VALUES ({','.join('?' * len(self._RUN_COLS))})",
                [row.get(c) for c in self._RUN_COLS])
        elif op == "condition":
            conn.execute(
                "INSERT INTO status_conditions (run_uuid, condition, "
                "created_at) VALUES (?,?,?)",
                (p["run_uuid"], p["condition"], p["created_at"]))
        elif op == "heartbeat":
            if p.get("step") is None:
                conn.execute("UPDATE runs SET heartbeat_at=? WHERE uuid=?",
                             (p["at"], p["uuid"]))
            else:
                step = int(p["step"])
                conn.execute(
                    f"UPDATE runs SET heartbeat_at=?, {self._HB_STEP_SQL} "
                    "WHERE uuid=?",
                    (p["at"], step, p["at"], p["at"], step, p["uuid"]))
        elif op == "delete_run":
            for table, col in (("runs", "uuid"),
                               ("status_conditions", "run_uuid"),
                               ("lineage", "run_uuid"),
                               ("launch_intents", "run_uuid"),
                               # a deleted pipeline takes its sweep's
                               # write-ahead window markers with it
                               ("trial_intents", "sweep_uuid")):
                conn.execute(f"DELETE FROM {table} WHERE {col}=?",
                             (p["uuid"],))
        elif op == "project":
            conn.execute(
                "INSERT OR IGNORE INTO projects (name, description, "
                "created_at) VALUES (?,?,?)",
                (p["name"], p.get("description"), p["created_at"]))
        elif op == "token":
            conn.execute(
                "INSERT OR REPLACE INTO tokens (id, token_hash, project, "
                "label, created_at, revoked) VALUES (?,?,?,?,?,?)",
                (p["id"], p["token_hash"], p.get("project"), p.get("label"),
                 p["created_at"], p.get("revoked", 0)))
        elif op == "token_revoke":
            conn.execute("UPDATE tokens SET revoked=1 WHERE id=?",
                         (p["id"],))
        elif op == "lineage":
            conn.execute(
                "INSERT INTO lineage (run_uuid, name, artifact) "
                "VALUES (?,?,?)",
                (p["run_uuid"], p.get("name"), p["artifact"]))
        elif op == "config":
            conn.execute(
                "INSERT OR REPLACE INTO control_config (key, value) "
                "VALUES (?,?)", (p["key"], p["value"]))
        elif op == "intent":
            cols = ("run_uuid", "lease_name", "lease_holder", "token",
                    "attempt", "state", "created_at", "updated_at")
            conn.execute(
                f"INSERT OR REPLACE INTO launch_intents ({','.join(cols)}) "
                f"VALUES ({','.join('?' * len(cols))})",
                [p.get(c) for c in cols])
        elif op == "trial_intent":
            cols = self._TRIAL_INTENT_COLS
            conn.execute(
                f"INSERT OR REPLACE INTO trial_intents ({','.join(cols)}) "
                f"VALUES ({','.join('?' * len(cols))})",
                [p.get(c) for c in cols])
        elif op == "quota":
            conn.execute(
                "INSERT OR REPLACE INTO quotas (tenant, chips, created_at, "
                "updated_at) VALUES (?,?,?,?)",
                (p["tenant"], int(p["chips"]), p["created_at"],
                 p["updated_at"]))
            with self._quota_lock:
                self._quota_cache[p["tenant"]] = int(p["chips"])
            self._register_quota_gauge(p["tenant"])
        elif op == "quota_delete":
            conn.execute("DELETE FROM quotas WHERE tenant=?", (p["tenant"],))
            with self._quota_lock:
                self._quota_cache.pop(p["tenant"], None)
        elif op == "cluster":
            conn.execute(
                "INSERT OR REPLACE INTO clusters (name, region, chip_type, "
                "capacity, created_at, updated_at) VALUES (?,?,?,?,?,?)",
                (p["name"], p.get("region"), p.get("chip_type"),
                 int(p.get("capacity") or 0), p["created_at"],
                 p["updated_at"]))
            with self._cluster_lock:
                self._cluster_cache[p["name"]] = {
                    c: p.get(c) for c in self._CLUSTER_COLS}
            self._register_cluster_gauges(p["name"])
        elif op == "cluster_delete":
            conn.execute("DELETE FROM clusters WHERE name=?", (p["name"],))
            with self._cluster_lock:
                self._cluster_cache.pop(p["name"], None)
                self._cluster_health.pop(p["name"], None)
        elif op == "alert":
            conn.execute(
                f"INSERT OR REPLACE INTO alerts "
                f"({','.join(self._ALERT_COLS)}) "
                f"VALUES ({','.join('?' * len(self._ALERT_COLS))})",
                [json.dumps(p.get(c)) if c == "labels"
                 and p.get(c) is not None else p.get(c)
                 for c in self._ALERT_COLS])
            # re-derive the firing gauge from the table — replay order is
            # commit order, so the count after each upsert is exact
            row = conn.execute(
                "SELECT COUNT(*) FROM alerts WHERE state='firing'"
            ).fetchone()
            self._alerts_firing = int(row[0]) if row else 0
        elif op == "promote":
            pass  # epoch adoption handled by the apply loop's max_epoch
        # unknown ops are skipped: a newer primary may log kinds an older
        # standby build doesn't know — it still converges on the ones it
        # does, and the operator upgrades before promoting

    # -- promotion + snapshots (ISSUE 7) -----------------------------------

    def promote(self) -> int:
        """Promote this store to primary: bump the store epoch and drop
        every agent lease — all in ONE transaction, logged to the
        changelog. Every fencing token minted before this moment dies here
        (its lease row is gone AND its epoch bits are old), so a write
        in flight from the dead primary's era gets a deterministic 409,
        never a silent landing; every ``?since=`` cursor from the old
        epoch gets a deterministic 410. Lifts read-only standby mode."""
        with self._transition_lock:
            with self._conn_ctx() as conn:
                if not conn.in_transaction:
                    conn.execute("BEGIN IMMEDIATE")
                conn.execute(
                    "UPDATE counters SET v=v+1 WHERE k='store_epoch'")
                epoch = conn.execute(
                    "SELECT v FROM counters WHERE k='store_epoch'"
                ).fetchone()[0]
                conn.execute("DELETE FROM agent_leases")
                self._epoch = int(epoch)
                self._log_change(conn, "promote", {"epoch": self._epoch})
        self._read_only = False
        return self._epoch

    def snapshot(self, dirpath: str) -> dict:
        """Crash-consistent snapshot into ``dirpath``: the whole DB via
        sqlite's online backup API, written tmp+fsync+rename with a
        sha256 manifest (the PR-4 checkpoint discipline) — a torn copy is
        detectable, never silently restored. Returns the manifest."""
        import hashlib
        import os

        os.makedirs(dirpath, exist_ok=True)
        tmp = os.path.join(dirpath,
                           f".snapshot-{uuid_mod.uuid4().hex[:8]}.tmp")
        dst = sqlite3.connect(tmp)
        try:
            with self._conn_ctx() as conn:
                conn.backup(dst)
            dst.commit()
            seq = dst.execute(
                "SELECT v FROM counters WHERE k='change_seq'").fetchone()[0]
            row = dst.execute(
                "SELECT v FROM counters WHERE k='store_epoch'").fetchone()
            epoch = int(row[0]) if row else 0
        finally:
            dst.close()
        h = hashlib.sha256()
        with open(tmp, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
            os.fsync(f.fileno())
        snap_path = os.path.join(dirpath, "snapshot.db")
        os.replace(tmp, snap_path)
        manifest = {"sha256": h.hexdigest(), "seq": int(seq),
                    "epoch": epoch, "created_at": _now()}
        mtmp = os.path.join(dirpath, ".manifest.tmp")
        with open(mtmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(mtmp, os.path.join(dirpath, "manifest.json"))
        try:
            dfd = os.open(dirpath, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        return manifest

    def _row_to_run(self, row) -> dict:
        self.stats["runs_deserialized"] += 1
        d = dict(zip(self._RUN_COLS, row))
        for c in self._JSON_COLS:
            d[c] = json.loads(d[c]) if d[c] else None
        return d

    @staticmethod
    def _params_to_inputs(spec: dict) -> Optional[dict]:
        """A run's queryable inputs default to its bound param values
        (upstream stored resolved params on the run row; compare/sort
        read them). Ref params carry an unresolved context expression as
        their value and context_only params aren't inputs — skip both."""
        params = spec.get("params") or {}
        out = {}
        for k, v in params.items():
            if isinstance(v, dict):
                if v.get("ref") or v.get("context_only") or v.get("contextOnly"):
                    continue
                out[k] = v.get("value")
            else:
                out[k] = v
        return out or None

    def create_run(
        self,
        project: str,
        spec: Optional[dict] = None,
        name: Optional[str] = None,
        kind: Optional[str] = None,
        inputs: Optional[dict] = None,
        meta: Optional[dict] = None,
        tags: Optional[list] = None,
        uuid: Optional[str] = None,
        original_uuid: Optional[str] = None,
        cloning_kind: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        created_by: Optional[str] = None,
        tenant: Optional[str] = None,
        fence=None,
    ) -> dict:
        return self.create_runs(project, [dict(
            spec=spec, name=name, kind=kind, inputs=inputs, meta=meta,
            tags=tags, uuid=uuid, original_uuid=original_uuid,
            cloning_kind=cloning_kind, pipeline_uuid=pipeline_uuid,
            created_by=created_by, tenant=tenant,
        )], fence=fence)[0]

    def create_runs(self, project: str, runs: list[dict],
                    fence=None) -> list[dict]:
        """Create many runs in ONE transaction (DAG/matrix fan-out: a
        16-wide suggestion batch is one commit, not 32). Each entry takes
        the same keyword fields as ``create_run``. Listeners fire after the
        commit, once per run, in order. ``fence=(lease_name, token)``
        rejects the whole batch with :class:`StaleLeaseError` when the
        token is no longer current — a stale agent's pipeline driver must
        not fan out children after a takeover."""
        self._check_writable()
        self.create_project(project)
        rows, conds = [], []
        uuids: list[str] = []
        parents: dict[str, Optional[dict]] = {}  # one lookup per batch
        for r in runs:
            spec = r.get("spec")
            inputs = r.get("inputs")
            if inputs is None and spec:
                # one place for every creation path (CLI, client, server, DAG
                # and schedule children, tuner trials pass explicit inputs)
                inputs = self._params_to_inputs(spec)
            created_by = r.get("created_by")
            if created_by is None and r.get("pipeline_uuid"):
                # pipeline children (DAG stages, sweep trials, schedule runs)
                # inherit their parent's owner — ownership filtering must not
                # split a user's pipeline from its stages (review r5)
                puid = r["pipeline_uuid"]
                if puid not in parents:
                    parents[puid] = self.get_run(puid)
                if parents[puid]:
                    created_by = parents[puid].get("created_by")
            # tenant (ISSUE 15): the accounting unit, stamped at create —
            # explicit wins (soaks/benches, admin backfills), pipeline
            # children inherit their parent's tenant (a sweep's trials
            # must bill the sweep's owner), otherwise derived from the
            # auth-token identity in created_by
            tenant = r.get("tenant")
            if tenant is None and r.get("pipeline_uuid"):
                puid = r["pipeline_uuid"]
                if puid not in parents:
                    parents[puid] = self.get_run(puid)
                if parents[puid]:
                    tenant = parents[puid].get("tenant")
            if tenant is None:
                from ..tenancy import tenant_of

                tenant = tenant_of(created_by)
            run_uuid = r.get("uuid") or uuid_mod.uuid4().hex
            uuids.append(run_uuid)
            rows.append((
                run_uuid, project, r.get("name"), r.get("kind"),
                V1Statuses.CREATED.value,
                json.dumps(spec) if spec else None,
                json.dumps(inputs) if inputs else None,
                json.dumps(r.get("meta")) if r.get("meta") else None,
                json.dumps(r.get("tags")) if r.get("tags") else None,
                r.get("original_uuid"), r.get("cloning_kind"),
                r.get("pipeline_uuid"), created_by, tenant,
            ))
            conds.append((
                run_uuid,
                json.dumps(V1StatusCondition.get_condition(V1Statuses.CREATED).to_dict()),
            ))
        t0 = time.perf_counter()
        with self._conn_ctx() as conn:
            try:
                self._check_fence(conn, fence)
                # timestamps + change seqs assigned INSIDE the write
                # transaction (the seq bump takes the writer lock), so
                # seq order matches commit order and ?since= pollers can
                # never skip a row committed after their snapshot
                now = _now()
                top = self._bump_seq(conn, len(rows))
                first = top - len(rows) + 1
                conn.executemany(
                    "INSERT INTO runs (uuid, project, name, kind, status, spec, inputs, meta, tags,"
                    " original_uuid, cloning_kind, pipeline_uuid, created_by, tenant, created_at,"
                    " updated_at, change_seq)"
                    " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                    [row + (now, now, first + i) for i, row in enumerate(rows)])
                conn.executemany(
                    "INSERT INTO status_conditions (run_uuid, condition, created_at) VALUES (?,?,?)",
                    [cond + (now,) for cond in conds])
                if self._replicate:
                    # row deltas ride the rows' own seqs; each condition
                    # draws a fresh one — all inside this transaction, so
                    # the whole batch replicates atomically in commit order
                    for i, u in enumerate(uuids):
                        self._log_run_row(conn, u, seq=first + i)
                    for run_uuid, cond_json in conds:
                        self._log_change(conn, "condition", {
                            "run_uuid": run_uuid, "condition": cond_json,
                            "created_at": now})
            except BaseException:
                # same hazard transition_many guards against: a mid-batch
                # failure (e.g. duplicate uuid) must not strand earlier
                # rows uncommitted for the next unrelated commit to flush
                # as ghost runs that never fired the change feed
                conn.rollback()
                raise
        self._h_write.observe(time.perf_counter() - t0)
        self._count_add(project, len(rows))
        # creation flows through the same feed as transitions so a
        # subscribed agent learns about new runs without scanning
        self._notify_listeners(
            [(u, V1Statuses.CREATED.value) for u in uuids])
        by_uuid = {r["uuid"]: r for r in self.get_runs(uuids)}
        return [by_uuid[u] for u in uuids]

    def _notify_listeners(self, events: list[tuple[str, str]]) -> None:
        """Fire ``(uuid, status)`` feed events in order. Always called
        AFTER the commit and outside any store lock — listeners may read
        the store."""
        for run_uuid, status in events:
            for listener in self._transition_listeners:
                try:
                    listener(run_uuid, status)
                except Exception:
                    import traceback

                    traceback.print_exc()

    def get_run(self, uuid: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._RUN_COLS)} FROM runs WHERE uuid=?", (uuid,)
            ).fetchone()
        return self._row_to_run(row) if row else None

    def get_runs(self, uuids: list[str]) -> list[dict]:
        """Fetch many runs by uuid in ONE query (the agent's dirty pass
        reads its whole dirty set this way). Missing uuids are silently
        absent; order is unspecified."""
        if not uuids:
            return []
        out: list[dict] = []
        with self._conn_ctx() as conn:
            # chunked: SQLite's default parameter cap is 999
            for i in range(0, len(uuids), 500):
                chunk = uuids[i:i + 500]
                rows = conn.execute(
                    f"SELECT {','.join(self._RUN_COLS)} FROM runs "
                    f"WHERE uuid IN ({','.join('?' * len(chunk))})",
                    chunk).fetchall()
                out += rows
        return [self._row_to_run(r) for r in out]

    @staticmethod
    def _runs_where(
        project=None, status=None, statuses=None, pipeline_uuid=None,
        created_by=None,
    ) -> tuple[str, list]:
        q, args = " WHERE 1=1", []
        if project:
            q += " AND project=?"
            args.append(project)
        if created_by:
            q += " AND created_by=?"
            args.append(created_by)
        if status:
            q += " AND status=?"
            args.append(status)
        if statuses:
            q += f" AND status IN ({','.join('?' * len(statuses))})"
            args.extend(statuses)
        if pipeline_uuid:
            q += " AND pipeline_uuid=?"
            args.append(pipeline_uuid)
        return q, args

    @staticmethod
    def run_cursor(run: dict) -> str:
        """Opaque keyset-pagination cursor for a listing row."""
        return f"{run['created_at']}|{run['uuid']}"

    def since_token(self, run: dict) -> str:
        """Resume token for incremental (``since``) fetches: the row's
        commit-ordered change_seq, epoch-qualified (:meth:`feed_token`) so
        a cursor can never silently survive a store failover."""
        return self.feed_token(run["change_seq"])

    def list_runs(
        self,
        project: Optional[str] = None,
        status: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        limit: int = 100,
        offset: int = 0,
        statuses: Optional[list[str]] = None,
        created_by: Optional[str] = None,
        order: str = "desc",
        cursor: Optional[str] = None,
        since: Optional[str] = None,
    ) -> list[dict]:
        """List runs, newest first by default (``order="asc"`` = FIFO).

        ``cursor`` (from :meth:`run_cursor`) keyset-paginates: rows strictly
        after the cursor position in the current order — O(page) however
        deep the listing, unlike OFFSET which scans every skipped row.
        ``since`` switches to incremental mode: rows whose commit-ordered
        ``change_seq`` is after the token (an int string — the bootstrap is
        :meth:`current_seq`, pages resume from :meth:`since_token` of the
        last delivered row), ordered by change_seq ascending, so pollers
        fetch O(delta) instead of O(all-runs) and can never lose a row to
        a stamp-before-commit race (overrides order/cursor)."""
        where, args = self._runs_where(
            project=project, status=status, statuses=statuses,
            pipeline_uuid=pipeline_uuid, created_by=created_by)
        q = f"SELECT {','.join(self._RUN_COLS)} FROM runs" + where
        if since is not None:
            # epoch-validated: a cursor from before a failover raises
            # StaleEpochError (HTTP 410) instead of silently missing the
            # replication-lag window's rows
            q += " AND change_seq>? ORDER BY change_seq ASC LIMIT ? OFFSET ?"
            args += [self.parse_since(since), limit, offset]
        else:
            if order not in ("desc", "asc"):
                raise ValueError(f"bad order {order!r}")
            if cursor is not None:
                c_at, _, c_uuid = cursor.partition("|")
                cmp = "<" if order == "desc" else ">"
                q += (f" AND (created_at{cmp}? OR "
                      f"(created_at=? AND uuid{cmp}?))")
                args += [c_at, c_at, c_uuid]
            # uuid tiebreak keeps the cursor total order stable when two
            # runs share a created_at microsecond (bulk create_runs does)
            q += (f" ORDER BY created_at {order.upper()}, "
                  f"uuid {order.upper()} LIMIT ? OFFSET ?")
            args += [limit, offset]
        with self._conn_ctx() as conn:
            rows = conn.execute(q, args).fetchall()
        runs = [self._row_to_run(r) for r in rows]
        # heartbeat staleness used to be observable only by the reaper
        # (ISSUE 5 satellite): stamp the age onto in-flight listing rows so
        # the dashboard can badge zombie-suspect runs without a second
        # query. Derived (never stored), and only present where it means
        # something — terminal/queued rows keep their exact shape.
        for d in runs:
            if d["status"] in (V1Statuses.STARTING.value,
                               V1Statuses.RUNNING.value):
                age = age_seconds(d.get("heartbeat_at") or d.get("started_at"))
                if age is not None:
                    d["heartbeat_age_s"] = round(age, 3)
                # progress-stall companion (ISSUE 8): how long the
                # reported training step has been FROZEN — the dashboard
                # badges step-stalled runs with it, same derived-never-
                # stored contract as heartbeat_age_s
                if d.get("heartbeat_step") is not None:
                    sage = age_seconds(d.get("heartbeat_step_at"))
                    if sage is not None:
                        d["heartbeat_step_age_s"] = round(sage, 3)
        return runs

    def count_runs(
        self,
        project: Optional[str] = None,
        status: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        statuses: Optional[list[str]] = None,
        created_by: Optional[str] = None,
    ) -> int:
        """Total rows matching the listing filters (pagination UIs).

        The project-only shape — what every paged-listing bootstrap asks
        — is served from the write-path row counters (O(1) dict lookup;
        ``stats['count_fast']``), with a drift-reconciling slow path
        every ``count_reconcile_every`` hits. Filtered shapes keep the
        exact COUNT(*) (``stats['count_slow']``)."""
        if (status is None and statuses is None and pipeline_uuid is None
                and created_by is None):
            return self._count_fast(project)
        self.stats["count_slow"] += 1
        where, args = self._runs_where(
            project=project, status=status, statuses=statuses,
            pipeline_uuid=pipeline_uuid, created_by=created_by)
        with self._conn_ctx() as conn:
            return conn.execute(
                "SELECT COUNT(*) FROM runs" + where, args).fetchone()[0]

    def _count_table(self) -> dict[str, int]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT project, COUNT(*) FROM runs GROUP BY project"
            ).fetchall()
        return {r[0]: int(r[1]) for r in rows}

    def _count_fast(self, project: Optional[str]) -> int:
        with self._count_lock:
            counts = self._run_counts
            self._count_hits += 1
            reconcile = (counts is None
                         or self._count_hits >= self.count_reconcile_every)
        if reconcile:
            # re-derive OUTSIDE the cache lock (the SQL read must not
            # serialize every fast-path caller), then swap + audit
            fresh = self._count_table()
            with self._count_lock:
                if (self._run_counts is not None
                        and self._run_counts != fresh):
                    self.stats["count_drift_repairs"] += 1
                self._run_counts = fresh
                self._count_hits = 0
                counts = fresh
        self.stats["count_fast"] += 1
        if project is not None:
            return counts.get(project, 0)
        return sum(counts.values())

    def _count_add(self, project: str, n: int) -> None:
        """Write-path counter maintenance (called AFTER the commit — a
        rolled-back batch never lands here)."""
        with self._count_lock:
            if self._run_counts is None:
                return
            self._run_counts[project] = max(
                self._run_counts.get(project, 0) + n, 0)

    def _count_invalidate(self) -> None:
        """Drop the cache where the write path can't see the delta
        (replication replay, snapshot restore): the next fast-path hit
        re-derives from SQL."""
        with self._count_lock:
            self._run_counts = None

    def update_run(self, uuid: str, fence=None, **fields: Any) -> Optional[dict]:
        self._check_writable()
        sets, args = [], []
        for k, v in fields.items():
            if k not in self._RUN_COLS or k in ("uuid", "change_seq"):
                raise ValueError(f"bad run field {k!r}")
            if k in self._JSON_COLS and v is not None and not isinstance(v, str):
                v = json.dumps(v)
            sets.append(f"{k}=?")
            args.append(v)
        sets.append("updated_at=?")
        args.append(_now())
        sets.append("change_seq=?")
        with self._conn_ctx() as conn:
            self._check_fence(conn, fence)
            seq = self._bump_seq(conn)
            args.append(seq)
            conn.execute(f"UPDATE runs SET {','.join(sets)} WHERE uuid=?",
                         args + [uuid])
            self._log_run_row(conn, uuid, seq=seq)
        return self.get_run(uuid)

    def merge_outputs(self, uuid: str, outputs: dict,
                      fence=None) -> Optional[dict]:
        # serialize the read-modify-write: concurrent writers (API
        # post_outputs, agent _collect_outputs, tuner merge) must not drop keys
        with self._transition_lock:
            run = self.get_run(uuid)
            if run is None:
                return None
            merged = dict(run.get("outputs") or {})
            merged.update(outputs)
            return self.update_run(uuid, fence=fence, outputs=merged)

    # the CASE keeps heartbeat_step_at pinned while the reported step
    # VALUE stays put (backfilling it when NULL) and moves it when the
    # step advances — its age IS the progress-stall signal, computed by
    # the store so the reaper and the dashboard can never disagree
    _HB_STEP_SQL = (
        "heartbeat_step_at=CASE WHEN heartbeat_step IS ? "
        "THEN COALESCE(heartbeat_step_at, ?) ELSE ? END, "
        "heartbeat_step=?")

    def heartbeat(self, uuid: str, step: Optional[int] = None,
                  anomalies: Optional[dict] = None,
                  rollbacks: Optional[int] = None,
                  incarnation: Optional[str] = None,
                  serve: Optional[dict] = None,
                  metrics: Optional[dict] = None) -> bool:
        """Renew a run's liveness lease (zombie-reaper input). Cheap direct
        UPDATE — no listeners fire, no updated_at churn. Replicated (as a
        tiny heartbeat delta, not a whole row) so a promoted standby's
        reaper sees real staleness, not replication-shaped staleness.

        ``step`` (ISSUE 8) is the pod's training progress: liveness and
        PROGRESS are separate signals, so the stall-aware reaper can tell
        a wedged step (fresh beats, frozen step) from a dead executor.
        ``anomalies``/``rollbacks`` are cumulative pod counters, folded
        into the ``polyaxon_train_*`` families by delta.

        ``metrics`` (ISSUE 20) is a :class:`~polyaxon_tpu.obs.history.
        SeriesBuffer` payload: the pod's recorded points, merged into
        this store's history recorder under the run's source key — the
        fleet-rollup half of ``GET /api/v1/metrics/history``. History is
        process-local like the registry itself (not replicated): a
        promoted standby rebuilds it from the beats that follow."""
        self._check_writable()
        with self._conn_ctx() as conn:
            now = _now()
            payload: dict[str, Any] = {"uuid": uuid, "at": now}
            if step is None:
                cur = conn.execute(
                    "UPDATE runs SET heartbeat_at=? WHERE uuid=?",
                    (now, uuid))
            else:
                step = int(step)
                payload["step"] = step
                cur = conn.execute(
                    f"UPDATE runs SET heartbeat_at=?, {self._HB_STEP_SQL} "
                    "WHERE uuid=?",
                    (now, step, now, now, step, uuid))
            if cur.rowcount > 0:
                if anomalies or rollbacks:
                    self._train_account(uuid, anomalies, rollbacks,
                                        incarnation)
                if serve is not None:
                    self._serve_account(uuid, serve, incarnation)
                if metrics is not None:
                    self.recorder.ingest(uuid[:12], metrics)
                self._log_change(conn, "heartbeat", payload)
        return cur.rowcount > 0

    def _train_account(self, uuid: str, anomalies: Optional[dict],
                       rollbacks: Optional[int],
                       incarnation: Optional[str]) -> None:
        """Cumulative pod counters -> monotonic store counters, by delta.

        The watermark is keyed on the reporting POD INCARNATION: two
        reporters relay the same pod's cumulatives (the pod's own API
        beat and the sidecar's progress.json bridge), and a stale lower
        value arriving late must clamp to zero — NOT read as a restart,
        which would re-add already-counted anomalies. A new incarnation
        (restarted attempt) starts a fresh watermark at zero, so its full
        count lands; reports with no incarnation at all (pre-r9 clients)
        fall back to the same-incarnation max-clamp, trading restart
        detection for never over-counting."""
        with self._train_lock:
            seen_inc, last = self._train_seen.get(uuid) or (None, {})
            if incarnation is not None and incarnation != seen_inc:
                last = {}  # fresh process: cumulatives restarted at zero

            def delta(key: str, new) -> int:
                if new is None:
                    return 0
                new = int(new)
                old = int(last.get(key, 0))
                last[key] = max(new, old)
                return max(new - old, 0)

            for kind in ("loss", "grad"):
                self.stats[f"train_anomalies_{kind}"] += delta(
                    f"anomalies_{kind}", (anomalies or {}).get(kind))
            self.stats["train_rollbacks"] += delta("rollbacks", rollbacks)
            self._train_seen[uuid] = (incarnation or seen_inc, last)

    def _serve_account(self, uuid: str, serve: dict,
                       incarnation: Optional[str]) -> None:
        """Serve-pod heartbeat payload -> traffic state + counters.

        Gauges (running/waiting/kv) are last-write-per-REPORTER: each
        replica is one reporter (keyed by tracking incarnation), and
        ``serve_traffic`` sums across reporters still fresh within
        ``serve_fresh_s`` — a dead replica ages out instead of pinning the
        scaler's signal. Cumulative counters delta with the same
        incarnation-keyed max-clamp as the train counters. Raw TTFT /
        inter-token observation lists (drained by the pod since its last
        beat) feed the store histograms directly, bounded per beat."""
        if not isinstance(serve, dict):
            return
        key = str(incarnation or serve.get("incarnation") or "-")

        def _num(v, default=0):
            try:
                return max(int(v), 0)
            except (TypeError, ValueError):
                return default

        with self._train_lock:
            per_run = self._serve_seen.setdefault(uuid, {})
            rec = per_run.setdefault(key, {"counters": {}})
            # monotonic: reporter freshness is a same-process duration —
            # an NTP step during a soak must not age every replica out of
            # (or back into) the autoscaler's signal at once
            rec["at"] = time.monotonic()
            # prune sibling reporters stale past a generous multiple of
            # the freshness window: replica-restart churn mints a new
            # incarnation per process, and the records would otherwise
            # grow without bound until delete_run. The trade: a reporter
            # silent past the horizon that RETURNS re-adds its full
            # cumulative count — the outage spool replays beats well
            # inside it.
            horizon = rec["at"] - 10 * self.serve_fresh_s
            for stale in [k for k, r in per_run.items()
                          if k != key and r.get("at", 0) < horizon]:
                per_run.pop(stale)
            rec["running"] = _num(serve.get("running"))
            rec["waiting"] = _num(serve.get("waiting"))
            rec["kv_used"] = _num(serve.get("kv_blocks_used"))
            rec["kv_total"] = _num(serve.get("kv_blocks_total"))
            # drain state (ISSUE 12): last-write-per-reporter like the
            # gauges — the agent's scale-down gate reads it per replica
            rec["draining"] = bool(serve.get("draining"))
            rec["drained"] = bool(serve.get("drained"))
            try:
                rec["replica"] = (int(serve["replica"])
                                  if serve.get("replica") is not None
                                  else None)
            except (TypeError, ValueError):
                rec["replica"] = None
            last = rec["counters"]

            def delta(key_: str, new) -> int:
                if new is None:
                    return 0
                new = _num(new)
                old = int(last.get(key_, 0))
                last[key_] = max(new, old)
                return max(new - old, 0)

            self.stats["serve_requests"] += delta(
                "requests", serve.get("requests_total"))
            self.stats["serve_tokens"] += delta(
                "tokens", serve.get("tokens_total"))
            self.stats["serve_rejected"] += delta(
                "rejected", serve.get("rejected_total"))
            self.stats["serve_preemptions"] += delta(
                "preempted", serve.get("preemptions_total"))
            # serving raw speed (ISSUE 17): prefix-cache + speculative
            # counters ride the same incarnation-keyed delta path, and
            # the shared-blocks gauge is last-write-per-reporter like
            # running/waiting/kv
            self.stats["serve_prefix_hits"] += delta(
                "prefix_hits", serve.get("prefix_cache_hits"))
            self.stats["serve_prefix_misses"] += delta(
                "prefix_misses", serve.get("prefix_cache_misses"))
            self.stats["serve_cow_copies"] += delta(
                "cow_copies", serve.get("cow_copies"))
            self.stats["serve_spec_proposed"] += delta(
                "spec_proposed", serve.get("spec_tokens_proposed"))
            self.stats["serve_spec_accepted"] += delta(
                "spec_accepted", serve.get("spec_tokens_accepted"))
            rec["shared_kv_blocks"] = _num(serve.get("shared_kv_blocks"))
        for field_, hist in (("ttft", self._h_serve_ttft),
                             ("itl", self._h_serve_itl)):
            obs = serve.get(field_)
            if isinstance(obs, (list, tuple)):
                for v in obs[:512]:
                    try:
                        hist.observe(float(v))
                    except (TypeError, ValueError):
                        pass

    def _serve_traffic_for_scrape(self) -> dict:
        """One aggregate snapshot per scrape window (1s TTL): the three
        gauge callbacks share it instead of walking the reporter records
        three times per /metrics render. The autoscaler keeps calling
        :meth:`serve_traffic` directly (always fresh)."""
        now = time.monotonic()
        ts, snap = self._serve_scrape_cache
        if snap is None or now - ts > 1.0:
            snap = self.serve_traffic()
            self._serve_scrape_cache = (now, snap)
        return snap

    def serve_traffic(self, uuid: Optional[str] = None) -> dict:
        """Aggregated live traffic across fresh reporters — the agent's
        autoscale input and the gauge families' source. ``uuid`` scopes to
        one service run; None aggregates every run."""
        now = time.monotonic()  # same clock as rec["at"] freshness stamps
        running = waiting = kv_used = kv_total = reporters = draining = 0
        shared_kv = 0
        with self._train_lock:
            runs = ([uuid] if uuid is not None
                    else list(self._serve_seen))
            for u in runs:
                per_run = self._serve_seen.get(u) or {}
                for key, rec in list(per_run.items()):
                    if now - rec.get("at", 0) > self.serve_fresh_s:
                        # counters watermark must survive a beat gap; only
                        # the GAUGE contribution ages out
                        continue
                    reporters += 1
                    running += rec.get("running", 0)
                    waiting += rec.get("waiting", 0)
                    kv_used += rec.get("kv_used", 0)
                    kv_total += rec.get("kv_total", 0)
                    shared_kv += rec.get("shared_kv_blocks", 0)
                    draining += 1 if rec.get("draining") else 0
        return {"running": running, "waiting": waiting,
                "reporters": reporters, "kv_used": kv_used,
                "kv_total": kv_total, "draining": draining,
                "shared_kv_blocks": shared_kv,
                "kv_utilization": (kv_used / kv_total if kv_total else 0.0)}

    def serve_replica_drain(self, uuid: str) -> dict:
        """Per-replica drain/traffic state for one service run — the
        agent's scale-down gate: a surplus pod is deleted only once its
        replica reports drained (or the drain deadline passes). Keyed by
        the replica index the pod stamps into its serve payload; the
        freshest reporter per replica wins (a restarted replica mints a
        new incarnation)."""
        now = time.monotonic()
        out: dict[int, dict] = {}
        with self._train_lock:
            for rec in (self._serve_seen.get(uuid) or {}).values():
                rep = rec.get("replica")
                if rep is None:
                    continue
                age = now - rec.get("at", 0)
                cur = out.get(rep)
                if cur is None or age < cur["age"]:
                    out[rep] = {
                        "age": age,
                        "draining": bool(rec.get("draining")),
                        "drained": bool(rec.get("drained")),
                        "running": rec.get("running", 0),
                        "waiting": rec.get("waiting", 0),
                    }
        return out

    def serve_progress(self, uuid: str) -> Optional[dict]:
        """Liveness-vs-progress split for serve replicas (ISSUE 12,
        mirroring heartbeat_step for trainers): cumulative completed
        requests (per-reporter counter watermarks, beat-gap proof) plus
        the currently-waiting depth across fresh reporters. The reaper's
        serving stall rule reaps a run whose ``requests_total`` freezes
        while ``waiting > 0`` — alive beats, dead engine. None when the
        run never reported serve traffic."""
        now = time.monotonic()
        with self._train_lock:
            per_run = self._serve_seen.get(uuid)
            if not per_run:
                return None
            total = sum(int(rec.get("counters", {}).get("requests", 0))
                        for rec in per_run.values())
            waiting = sum(rec.get("waiting", 0) for rec in per_run.values()
                          if now - rec.get("at", 0) <= self.serve_fresh_s)
        return {"requests_total": total, "waiting": waiting}

    def count_serve_retries(self, n: int = 1) -> None:
        """Bump the request-retry counter (ISSUE 12) — wire it as a
        ServeFront's ``on_retry``; pods can't see client-side retries,
        and the family's value_fn reads this stat."""
        with self._train_lock:
            self.stats["serve_request_retries"] += int(n)

    def delete_run(self, uuid: str) -> bool:
        self._check_writable()
        with self._train_lock:  # vs a racing heartbeat's re-insert
            self._train_seen.pop(uuid, None)  # watermark dies with the row
            self._serve_seen.pop(uuid, None)
        with self._conn_ctx() as conn:
            # project read BEFORE the delete: the change feed scopes
            # deletions per-project (ISSUE 14), and a post-delete lookup
            # can only answer None
            row = conn.execute("SELECT project FROM runs WHERE uuid=?",
                               (uuid,)).fetchone()
            cur = conn.execute("DELETE FROM runs WHERE uuid=?", (uuid,))
            conn.execute("DELETE FROM status_conditions WHERE run_uuid=?", (uuid,))
            conn.execute("DELETE FROM lineage WHERE run_uuid=?", (uuid,))
            conn.execute("DELETE FROM launch_intents WHERE run_uuid=?", (uuid,))
            conn.execute("DELETE FROM trial_intents WHERE sweep_uuid=?",
                         (uuid,))
            if cur.rowcount > 0:
                self._log_change(conn, "delete_run", {
                    "uuid": uuid, "project": row[0] if row else None})
        if cur.rowcount > 0 and row:
            self._count_add(row[0], -1)
        return cur.rowcount > 0

    # -- statuses ----------------------------------------------------------

    def transition(
        self, uuid: str, status: str, reason: Optional[str] = None,
        message: Optional[str] = None, force: bool = False, fence=None,
    ) -> tuple[Optional[dict], bool]:
        """Apply a status transition if legal. Returns (run, changed).
        Atomic: the check + condition insert + status update hold one lock so
        concurrent writers (agent vs executor threads) cannot interleave —
        e.g. a late 'failed' from a killed process must not overwrite
        'stopped'."""
        return self.transition_many([(uuid, status, reason, message, force)],
                                    fence=fence)[0]

    def _get_run_conn(self, conn, uuid: str) -> Optional[dict]:
        row = conn.execute(
            f"SELECT {','.join(self._RUN_COLS)} FROM runs WHERE uuid=?", (uuid,)
        ).fetchone()
        return self._row_to_run(row) if row else None

    def transition_many(
        self, transitions: list[tuple], fence=None,
    ) -> list[tuple[Optional[dict], bool]]:
        """Apply many status transitions in ONE lock hold + ONE commit.

        ``transitions``: ``(uuid, status[, reason[, message[, force]]])``
        tuples, applied in order — later entries see earlier ones (the
        reconciler's restart path walks running -> retrying -> queued ->
        scheduled on one run). Returns (run, changed) per entry, same
        semantics as :meth:`transition`. Listeners fire after the batch
        commits, in order, only for applied transitions — so a burst of
        lifecycle updates is one fsync, not 3 transactions each.
        ``fence=(lease_name, token)`` rejects the whole batch with
        :class:`StaleLeaseError` when a newer lease acquisition exists —
        a stale agent's promotion wave cannot land after a takeover."""
        self._check_writable()
        results: list[tuple[Optional[dict], bool]] = []
        applied: list[tuple[str, str]] = []
        sched_ages: list[float] = []
        t0 = time.perf_counter()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                try:
                    self._check_fence(conn, fence)
                    self._transition_batch(conn, transitions, results, applied,
                                           sched_ages)
                except BaseException:
                    # a mid-batch error (bad status string, corrupt row)
                    # must not strand earlier entries' writes uncommitted
                    # on the shared connection — the next unrelated commit
                    # would flush them WITHOUT their listeners ever firing
                    conn.rollback()
                    applied.clear()
                    sched_ages.clear()
                    raise
        self._h_write.observe(time.perf_counter() - t0)
        # schedule-latency samples flush only after the batch COMMITS: a
        # rolled-back batch also rolls back started_at, so the retried
        # RUNNING edge would otherwise observe the same run twice
        for age in sched_ages:
            self._h_sched.observe(age)
        # observers run OUTSIDE the lock (they may read the store) and only
        # for transitions that actually happened — hooks keyed off rejected
        # late reports (a killed process's 'failed' after 'stopped') never
        # fire with the wrong status
        self._notify_listeners(applied)
        return results

    def _transition_batch(self, conn, transitions, results, applied,
                          sched_ages: Optional[list] = None) -> None:
        for t in transitions:
            uuid, status = t[0], t[1]
            reason = t[2] if len(t) > 2 else None
            message = t[3] if len(t) > 3 else None
            force = bool(t[4]) if len(t) > 4 else False
            run = self._get_run_conn(conn, uuid)
            if run is None:
                results.append((None, False))
                continue
            src = V1Statuses(run["status"])
            dst = V1Statuses(status)
            if (not force or src in DONE_STATUSES) and not can_transition(src, dst):
                results.append((run, False))
                continue
            cond = V1StatusCondition.get_condition(
                dst, reason=reason, message=message)
            now = _now()
            seq = self._bump_seq(conn)
            sets = ["status=?", "updated_at=?", "change_seq=?"]
            args: list[Any] = [dst.value, now, seq]
            if dst == V1Statuses.RUNNING:
                # every attempt reports progress from scratch (ISSUE 8):
                # clearing the step fields on the running edge resets the
                # stall clocks — a restarted pod's compile/restore window
                # must never be judged against the DEAD attempt's frozen
                # progress (a stale step would cascade stall-reaps until
                # the retry budget burned out)
                sets.append("heartbeat_step=NULL")
                sets.append("heartbeat_step_at=NULL")
            if dst == V1Statuses.RUNNING and not run.get("started_at"):
                sets.append("started_at=?")
                args.append(now)
                # schedule latency stamped with the FIRST running edge
                # (retries don't re-observe: started_at is already set);
                # the caller observes it only after the batch commits —
                # the exact created->running interval scripts/
                # sched_bench.py measures from its listener
                age = age_seconds(run.get("created_at"))
                if age is not None and sched_ages is not None:
                    sched_ages.append(age)
            if is_done(dst):
                sets.append("finished_at=?")
                args.append(now)
            cond_json = json.dumps(cond.to_dict())
            conn.execute(
                "INSERT INTO status_conditions (run_uuid, condition, created_at) VALUES (?,?,?)",
                (uuid, cond_json, now),
            )
            conn.execute(
                f"UPDATE runs SET {','.join(sets)} WHERE uuid=?",
                args + [uuid])
            self._log_run_row(conn, uuid, seq=seq)
            if self._replicate:
                self._log_change(conn, "condition", {
                    "run_uuid": uuid, "condition": cond_json,
                    "created_at": now})
            results.append((self._get_run_conn(conn, uuid), True))
            applied.append((uuid, dst.value))

    def annotate_status(self, uuid: str, reason: str,
                        message: Optional[str] = None, fence=None,
                        meta_patch: Optional[dict] = None) -> Optional[dict]:
        """Append a status condition at the run's CURRENT status without
        transitioning it — the loud-but-not-lifecycle writes (ISSUE 15):
        ``queued(OverQuota)`` parking, ``UnknownTenant`` quota fallback.
        ``meta_patch`` merges keys into run.meta in the same transaction
        (``None`` values delete keys), so "parked" is one commit: the
        condition for the history, the meta flag for listings. Fenced
        like every lifecycle write; fires no transition listeners (the
        status did not change — re-waking the scheduler over its own
        annotation would churn)."""
        self._check_writable()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                run = self._get_run_conn(conn, uuid)
                if run is None:
                    return None
                cond = V1StatusCondition.get_condition(
                    V1Statuses(run["status"]), reason=reason,
                    message=message)
                now = _now()
                seq = self._bump_seq(conn)
                cond_json = json.dumps(cond.to_dict())
                conn.execute(
                    "INSERT INTO status_conditions (run_uuid, condition, "
                    "created_at) VALUES (?,?,?)", (uuid, cond_json, now))
                sets = ["updated_at=?", "change_seq=?"]
                args: list[Any] = [now, seq]
                if meta_patch:
                    meta = dict(run.get("meta") or {})
                    for k, v in meta_patch.items():
                        if v is None:
                            meta.pop(k, None)
                        else:
                            meta[k] = v
                    sets.append("meta=?")
                    args.append(json.dumps(meta))
                conn.execute(
                    f"UPDATE runs SET {','.join(sets)} WHERE uuid=?",
                    args + [uuid])
                self._log_run_row(conn, uuid, seq=seq)
                if self._replicate:
                    self._log_change(conn, "condition", {
                        "run_uuid": uuid, "condition": cond_json,
                        "created_at": now})
                return self._get_run_conn(conn, uuid)

    def add_transition_listener(self, fn) -> None:
        """Register ``fn(uuid, new_status)`` called after every applied
        transition (any writer: agent, executor callbacks, API clients)."""
        self._transition_listeners.append(fn)

    def find_cached_run(self, project: str, cache_key: str) -> Optional[dict]:
        """Most recent succeeded run in ``project`` whose meta.cache_key
        matches — SQL-side so the lookup is one row, not a page scan."""
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._RUN_COLS)} FROM runs "
                "WHERE project=? AND status='succeeded' "
                "AND json_extract(meta, '$.cache_key')=? "
                "ORDER BY created_at DESC LIMIT 1",
                (project, cache_key),
            ).fetchone()
        return self._row_to_run(row) if row else None

    def get_statuses(self, uuid: str) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT condition FROM status_conditions WHERE run_uuid=? ORDER BY id",
                (uuid,),
            ).fetchall()
        return [json.loads(r[0]) for r in rows]

    # -- lineage -----------------------------------------------------------

    def add_lineage(self, uuid: str, artifact: dict) -> None:
        self._check_writable()
        with self._conn_ctx() as conn:
            art_json = json.dumps(artifact)
            conn.execute(
                "INSERT INTO lineage (run_uuid, name, artifact) VALUES (?,?,?)",
                (uuid, artifact.get("name"), art_json),
            )
            self._log_change(conn, "lineage", {
                "run_uuid": uuid, "name": artifact.get("name"),
                "artifact": art_json})

    def get_lineage(self, uuid: str) -> list[dict]:
        with self._conn_ctx() as conn:
            rows = conn.execute(
                "SELECT artifact FROM lineage WHERE run_uuid=? ORDER BY id", (uuid,)
            ).fetchall()
        return [json.loads(r[0]) for r in rows]

    # -- SLO alerts (ISSUE 20) ---------------------------------------------

    _ALERT_COLS = ("name", "slo", "state", "severity", "value", "reason",
                   "labels", "transitions", "first_at", "pending_at",
                   "fired_at", "resolved_at", "last_notified_at",
                   "updated_at")

    _ALERT_STATES = ("pending", "firing", "resolved")

    def _row_to_alert(self, row) -> dict:
        d = dict(zip(self._ALERT_COLS, row))
        if d.get("labels"):
            try:
                d["labels"] = json.loads(d["labels"])
            except (TypeError, ValueError):
                d["labels"] = {}
        else:
            d["labels"] = {}
        return d

    def get_alert(self, name: str) -> Optional[dict]:
        with self._conn_ctx() as conn:
            row = conn.execute(
                f"SELECT {','.join(self._ALERT_COLS)} FROM alerts "
                "WHERE name=?", (name,)).fetchone()
        return self._row_to_alert(row) if row else None

    def list_alerts(self, state: Optional[str] = None) -> list[dict]:
        """Alert rows, firing-first then most recently updated — the
        order the dashboard panel and ``polyaxon alerts ls`` both show."""
        with self._conn_ctx() as conn:
            if state is not None:
                rows = conn.execute(
                    f"SELECT {','.join(self._ALERT_COLS)} FROM alerts "
                    "WHERE state=? ORDER BY updated_at DESC",
                    (state,)).fetchall()
            else:
                rows = conn.execute(
                    f"SELECT {','.join(self._ALERT_COLS)} FROM alerts "
                    "ORDER BY CASE state WHEN 'firing' THEN 0 "
                    "WHEN 'pending' THEN 1 ELSE 2 END, updated_at DESC"
                ).fetchall()
        return [self._row_to_alert(r) for r in rows]

    def upsert_alert(self, name: str, state: str, slo: Optional[str] = None,
                     severity: Optional[str] = None,
                     value: Optional[float] = None,
                     reason: Optional[str] = None,
                     labels: Optional[dict] = None,
                     mark_notified: bool = False, fence=None) -> dict:
        """Record an alert state — the SLO engine's one write verb.

        Exactly-once semantics come from two properties: the write is
        FENCED (a deposed evaluator's upsert dies in ``_check_fence``
        like a stale run transition), and it is a DEDUP'D upsert — a
        same-state write changes nothing, bumps no transition counter,
        and logs no changelog record, so two well-behaved evaluators
        racing the same observation converge on one persisted edge.
        ``mark_notified`` stamps the notification watermark on the SAME
        transaction as the transition it announces: a takeover between
        "alert fired" and "notification recorded" re-notifies (at-least-
        once paging), but can never record a notification that lost its
        transition. Returns the row plus ``changed``."""
        if state not in self._ALERT_STATES:
            raise ValueError(
                f"alert state must be one of {self._ALERT_STATES}, "
                f"got {state!r}")
        self._check_writable()
        with self._transition_lock:
            with self._conn_ctx() as conn:
                self._check_fence(conn, fence)
                row = conn.execute(
                    f"SELECT {','.join(self._ALERT_COLS)} FROM alerts "
                    "WHERE name=?", (name,)).fetchone()
                cur = self._row_to_alert(row) if row else None
                now = _now()
                if cur is not None and cur["state"] == state:
                    if mark_notified:
                        conn.execute(
                            "UPDATE alerts SET last_notified_at=?, "
                            "value=COALESCE(?, value), updated_at=? "
                            "WHERE name=?", (now, value, now, name))
                        cur["last_notified_at"] = now
                        cur["updated_at"] = now
                        if value is not None:
                            cur["value"] = value
                    return {**cur, "changed": False}
                new = {
                    "name": name,
                    "slo": slo if slo is not None
                    else (cur or {}).get("slo"),
                    "state": state,
                    "severity": severity if severity is not None
                    else (cur or {}).get("severity"),
                    "value": value,
                    "reason": reason,
                    "labels": labels if labels is not None
                    else (cur or {}).get("labels") or {},
                    "transitions": ((cur or {}).get("transitions") or 0) + 1,
                    "first_at": (cur or {}).get("first_at") or now,
                    # pending_at restarts per episode: dwell timing must
                    # measure THIS breach, not one resolved hours ago
                    "pending_at": now if state == "pending"
                    else (cur or {}).get("pending_at"),
                    "fired_at": now if state == "firing"
                    else (cur or {}).get("fired_at"),
                    "resolved_at": now if state == "resolved"
                    else (cur or {}).get("resolved_at"),
                    "last_notified_at": now if mark_notified
                    else (cur or {}).get("last_notified_at"),
                    "updated_at": now,
                }
                conn.execute(
                    f"INSERT OR REPLACE INTO alerts "
                    f"({','.join(self._ALERT_COLS)}) "
                    f"VALUES ({','.join('?' * len(self._ALERT_COLS))})",
                    [json.dumps(new[c]) if c == "labels" else new[c]
                     for c in self._ALERT_COLS])
                if state == "firing":
                    self._alerts_firing += 1
                elif cur is not None and cur["state"] == "firing":
                    self._alerts_firing -= 1
                self.stats[f"alert_transitions_{state}"] += 1
                if self._replicate:
                    self._log_change(conn, "alert", new)
                return {**new, "changed": True}

    def resolve_alert(self, name: str, value: Optional[float] = None,
                      reason: Optional[str] = None, fence=None) -> dict:
        """Transition an alert to resolved. A missing row resolves to a
        no-op (never creates a resolved ghost); an already-resolved row
        dedups like any same-state upsert."""
        cur = self.get_alert(name)
        if cur is None:
            return {"name": name, "state": None, "changed": False}
        return self.upsert_alert(name, "resolved", value=value,
                                 reason=reason, fence=fence)


class FencedStore:
    """Write-fencing proxy over a :class:`Store` (or any store-shaped
    wrapper, e.g. the chaos FaultyStore).

    Every lifecycle write — run creation, transition batches, run updates,
    launch-intent stamping — is stamped with the caller's CURRENT lease
    fence, read lazily per call from ``fence_source`` (None = no lease
    held = unfenced, preserving direct-call test semantics). The agent
    hands this proxy to everything that writes on its behalf (pipeline
    drivers, the zombie reaper, executor callbacks), so a takeover fences
    out every code path at once instead of each call site remembering to.

    Sharded mode (ISSUE 6): ``fence_source`` may return a CALLABLE
    ``run_uuid -> fence`` instead of a fence tuple. Each write is then
    stamped with the token of the shard that owns THAT run, so a stale
    shard owner is write-rejected per-shard, not per-agent:

    - single-run verbs resolve the fence from their uuid argument;
    - ``create_run(s)`` resolve it from the entries' ``pipeline_uuid`` —
      the authority to fan out children is ownership of the PARENT
      pipeline's shard (parentless creations are client-equivalent and
      go unfenced);
    - ``transition_many`` splits the batch into per-shard sub-batches
      BEFORE the transaction: a fence rejection from a concurrent shard
      owner rejects only that shard's sub-batch (its entries come back
      as ``(current row, False)``) while every other sub-batch commits.

    ``on_stale`` fires (once per rejection, outside any store lock). With
    a tuple fence source it is called with no arguments and the
    :class:`StaleLeaseError` propagates (pre-shard semantics); with a
    callable source it receives the rejected LEASE NAME so the caller can
    demote exactly that shard."""

    _FENCED = ("create_run", "create_runs", "transition", "transition_many",
               "update_run", "merge_outputs", "record_launch_intent",
               "mark_launched", "adopt_launch", "annotate_status",
               "place_run",
               # sweep write-ahead windows (ISSUE 19): first positional arg
               # is the sweep (pipeline) uuid, so the default resolver
               # fences them with the PIPELINE's shard lease — the same
               # lease that authorizes the tuner's create_runs
               "record_trial_intents", "mark_trials_created",
               # SLO alert edges (ISSUE 20): first positional arg is the
               # alert NAME — the default resolver hashes it onto a shard
               # lease exactly like a run uuid, so a sharded fleet splits
               # the alert space and a deposed evaluator's edge dies here
               "upsert_alert", "resolve_alert")

    def __init__(self, inner, fence_source, on_stale=None):
        import inspect

        self._inner = inner
        self._fence_source = fence_source
        self._on_stale = on_stale
        self._on_stale_takes_name = False
        if on_stale is not None:
            try:
                self._on_stale_takes_name = bool(
                    inspect.signature(on_stale).parameters)
            except (TypeError, ValueError):
                pass

    def _notify_stale(self, lease_name: Optional[str]) -> None:
        if self._on_stale is None:
            return
        if self._on_stale_takes_name:
            self._on_stale(lease_name)
        else:
            self._on_stale()

    def _resolve_fence(self, verb: str, src, a: tuple, kw: dict):
        """Concrete ``(name, token)`` (or None) for one call under a
        callable (sharded) fence source."""
        if verb in ("create_run", "create_runs"):
            if verb == "create_runs":
                entries = a[1] if len(a) > 1 else kw.get("runs") or []
            else:
                entries = [kw]
            puid = next((r.get("pipeline_uuid") for r in entries
                         if r.get("pipeline_uuid")), None)
            return src(puid) if puid else None
        uuid = a[0] if a else kw.get("uuid") or kw.get("run_uuid")
        return src(uuid)

    def transition_many(self, transitions: list[tuple], fence=None,
                        **kw: Any) -> list[tuple[Optional[dict], bool]]:
        src = self._fence_source() if fence is None else fence
        if not callable(src):
            try:
                return self._inner.transition_many(transitions, fence=src,
                                                   **kw)
            except StaleLeaseError as e:
                self._notify_stale(e.lease_name)
                raise
        # sharded: one sub-batch (one lock hold + one commit) per distinct
        # shard fence, in first-appearance order; a stale sub-batch is
        # rejected alone and reported as unapplied
        groups: dict = {}
        order: list = []
        for i, t in enumerate(transitions):
            f = src(t[0])
            if f not in groups:
                groups[f] = []
                order.append(f)
            groups[f].append((i, t))
        results: list = [None] * len(transitions)
        for f in order:
            entries = groups[f]
            try:
                out = self._inner.transition_many(
                    [t for _, t in entries], fence=f, **kw)
            except StaleLeaseError:
                self._notify_stale(f[0] if f else None)
                for i, t in entries:
                    results[i] = (self._inner.get_run(t[0]), False)
                continue
            for (i, _), r in zip(entries, out):
                results[i] = r
        return results

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in self._FENCED and callable(attr):
            def _fenced(*a: Any, _attr=attr, _name=name, **kw: Any) -> Any:
                if "fence" not in kw:
                    src = self._fence_source()
                    kw["fence"] = (self._resolve_fence(_name, src, a, kw)
                                   if callable(src) else src)
                try:
                    return _attr(*a, **kw)
                except StaleLeaseError as e:
                    self._notify_stale(e.lease_name)
                    raise

            return _fenced
        return attr
