"""Store survivability (ISSUE 7): warm-standby replication + failover.

The SQLite store behind the API was the control plane's last single point
of failure — every guarantee (leases, fencing tokens, launch intents, the
``?since=`` change feed) flowed through one file behind one process. This
module closes that:

- :class:`ReplicatedStandby` tails a primary's commit-ordered changelog
  (``Store.get_changelog`` in-process, or :class:`HttpReplicationSource`
  over the wire) into a read-only target store, optionally bootstrapping
  from a sha256-manifested snapshot. It promotes the target — bumping the
  store epoch, which fences out every pre-failover token and feed cursor —
  either explicitly or on a lease-style liveness rule: the primary vouches
  for itself by being pollable; ``promote_after`` seconds of silence is a
  dead primary.
- :class:`FailoverStore` is the store-verb twin of the client's
  multi-endpoint rotation: an ordered list of store handles, rotating to
  the next on :class:`StoreUnavailableError` (the ``kill_store()`` chaos
  gate raises it; a real deployment's network client would too). The
  agent plugs it in where a single ``Store`` went; everything above
  (FencedStore, leases, resync) composes unchanged.

Split-brain honesty (docs/RESILIENCE.md "Store crash matrix"): a
partitioned-but-alive primary keeps accepting writes after the standby
promotes. The epoch fence protects every *failed-over* writer (their new
tokens/cursors bind them to the new primary), and clients reach endpoints
in ORDER, so traffic converges on whichever endpoint answers first — but
writes accepted by an isolated old primary after promotion are lost when
it is retired. The operator contract is the usual one: fence the old host
(kill it or partition it away from clients) before trusting the new
history.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
import traceback
from typing import Any, Optional

from .store import CompactedLogError, Store


class StoreUnavailableError(ConnectionError):
    """The store host is unreachable (process dead, network gone) — the
    failover front rotates to the next endpoint on this, exactly like the
    HTTP client rotates on a connection refusal."""


class TornSnapshotError(ValueError):
    """snapshot.db does not match its sha256 manifest (torn copy, partial
    upload, bit rot) — restoring it would silently diverge; callers fall
    back to an older snapshot or a full changelog tail."""


# -- snapshots ---------------------------------------------------------------


def verify_snapshot(dirpath: str) -> dict:
    """Validate ``dirpath``'s snapshot against its manifest and return the
    manifest. Raises :class:`TornSnapshotError` on any mismatch (missing
    files count: a manifest without its payload IS a torn snapshot)."""
    import hashlib

    snap = os.path.join(dirpath, "snapshot.db")
    man = os.path.join(dirpath, "manifest.json")
    if not (os.path.isfile(snap) and os.path.isfile(man)):
        raise TornSnapshotError(f"incomplete snapshot in {dirpath!r}")
    with open(man, encoding="utf-8") as f:
        manifest = json.load(f)
    h = hashlib.sha256()
    with open(snap, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != manifest.get("sha256"):
        raise TornSnapshotError(
            f"snapshot {snap!r} sha256 mismatch "
            f"(manifest {manifest.get('sha256')!r}, file {h.hexdigest()!r})")
    return manifest


def restore_snapshot(dirpath: str, store: Store) -> dict:
    """Load a verified snapshot INTO ``store`` (standby bootstrap) and
    refresh the store's derived identity (epoch, applied changelog seq).
    Returns the manifest. The target's prior contents are replaced."""
    manifest = verify_snapshot(dirpath)
    src = sqlite3.connect(os.path.join(dirpath, "snapshot.db"))
    try:
        with store._conn_ctx() as conn:
            src.backup(conn)
    finally:
        src.close()
    with store._conn_ctx() as conn:
        row = conn.execute(
            "SELECT v FROM counters WHERE k='store_epoch'").fetchone()
        store._epoch = int(row[0]) if row else 0
        row = conn.execute("SELECT MAX(seq) FROM changelog").fetchone()
        store._applied_seq = int(row[0]) if row and row[0] else 0
    # the restore replaced the run table wholesale behind the write
    # path's back — the count_runs row counters must re-derive
    store._count_invalidate()
    return manifest


# -- replication sources -----------------------------------------------------


class HttpReplicationSource:
    """Changelog/snapshot reads from a remote primary's API (``GET
    /api/v1/changelog``, ``GET /api/v1/store/snapshot``) — what a standby
    *server* tails when the primary is another host. Connection-level
    failures surface as :class:`StoreUnavailableError`, which is the
    standby's promote-on-silence signal."""

    def __init__(self, url: str, auth_token: Optional[str] = None,
                 timeout: float = 10.0):
        import requests

        self.url = url.rstrip("/")
        self.timeout = timeout
        self._session = requests.Session()
        token = auth_token if auth_token is not None \
            else os.environ.get("PLX_AUTH_TOKEN")
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        self._span = {"seq": 0, "epoch": 0}

    def _get(self, path: str, ok_statuses: tuple = (), **kw: Any):
        import requests

        try:
            resp = self._session.get(f"{self.url}{path}",
                                     timeout=self.timeout, **kw)
        except (requests.exceptions.ConnectionError,
                requests.exceptions.Timeout) as e:
            raise StoreUnavailableError(
                f"primary {self.url} unreachable: {e}") from e
        # ANY HTTP answer — 5xx included — is a LIVE primary (a 500 is
        # SQLITE_BUSY weather behind the handler, not a corpse), so it
        # must never feed the standby's promote-on-silence rule: only
        # connection-level failures are. The cost is that a dead primary
        # hidden behind an LB answering 502s needs a manual promotion —
        # the safe direction; the alternative is a split brain every time
        # the primary has a bad burst.
        if resp.status_code in ok_statuses:
            return resp
        resp.raise_for_status()
        return resp

    def get_changelog(self, after_seq: int = 0,
                      limit: int = 500) -> list[dict]:
        resp = self._get("/api/v1/changelog",
                         params={"after": after_seq, "limit": limit},
                         ok_statuses=(410,))
        if resp.status_code == 410:
            body = resp.json()
            raise CompactedLogError(int(after_seq),
                                    int(body.get("floor", 0)))
        doc = resp.json()
        self._span = {"seq": doc["seq"], "epoch": doc["epoch"]}
        return doc["rows"]

    def changelog_span(self) -> dict:
        return dict(self._span)

    def fetch_snapshot(self, dest_dir: str) -> dict:
        """Download the primary's snapshot + manifest into ``dest_dir``
        (bootstrap for an empty standby). Streamed in chunks — the
        snapshot is the whole DB, and buffering it in memory would OOM
        exactly the large deployments failover exists for."""
        resp = self._get("/api/v1/store/snapshot", stream=True)
        os.makedirs(dest_dir, exist_ok=True)
        tmp = os.path.join(dest_dir, ".snapshot.tmp")
        with open(tmp, "wb") as f:
            for chunk in resp.iter_content(1 << 20):
                f.write(chunk)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(dest_dir, "snapshot.db"))
        manifest = {
            "sha256": resp.headers["X-Snapshot-Sha256"],
            "seq": int(resp.headers["X-Snapshot-Seq"]),
            "epoch": int(resp.headers["X-Snapshot-Epoch"]),
            "created_at": resp.headers.get("X-Snapshot-Created-At"),
        }
        with open(os.path.join(dest_dir, "manifest.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f)
        return manifest


# -- the warm standby --------------------------------------------------------


class ReplicatedStandby:
    """Tail a primary's changelog into a read-only target store; promote
    the target when the primary dies.

    ``promote_after`` is the lease-style store-primary rule: every
    successful poll is a lease renewal by the primary; ``promote_after``
    seconds without one means the lease expired and the standby takes
    over. ``None`` keeps promotion manual (operator/harness calls
    :meth:`promote`). The 2x-lease-TTL takeover bound the agent layer
    already proves then stacks on top: store promotion at T, agent shard
    re-acquisition within 2x agent TTL after that.
    """

    def __init__(self, source, target: Store, poll_interval: float = 0.1,
                 promote_after: Optional[float] = None,
                 snapshot_dir: Optional[str] = None, metrics=None):
        self.source = source
        self.target = target
        self.poll_interval = poll_interval
        self.promote_after = promote_after
        self.snapshot_dir = snapshot_dir
        target.set_read_only(True)
        self.applied_seq = target._applied_seq
        self.source_seq = self.applied_seq
        self.healthy = True
        self.promoted = False
        self.epoch: Optional[int] = None
        self._last_ok = time.monotonic()
        self._compaction_warned = False
        self._divergence_warned = False
        self._error_warned = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        reg = metrics if metrics is not None else target.metrics
        reg.gauge(
            "polyaxon_store_replication_lag",
            "Changelog rows the standby is behind the primary "
            "(0 = caught up; frozen at the last observed span when the "
            "primary is unreachable)",
            value_fn=lambda: float(self.lag))
        reg.gauge(
            "polyaxon_store_replication_healthy",
            "1 while the standby's last changelog poll succeeded",
            value_fn=lambda: 1.0 if self.healthy else 0.0)

    @property
    def lag(self) -> int:
        return max(self.source_seq - self.applied_seq, 0)

    def bootstrap(self) -> Optional[dict]:
        """Restore from ``snapshot_dir`` when the target is empty. A torn
        snapshot is detected (sha256 manifest) and SKIPPED — the standby
        falls back to tailing the full changelog from seq 0 rather than
        restoring silently-divergent state."""
        if not self.snapshot_dir or self.applied_seq > 0:
            return None
        try:
            manifest = restore_snapshot(self.snapshot_dir, self.target)
        except TornSnapshotError as e:
            print(f"[standby] snapshot rejected ({e}); falling back to a "
                  "full changelog tail", flush=True)
            return None
        self.applied_seq = self.target._applied_seq
        self.source_seq = max(self.source_seq, self.applied_seq)
        return manifest

    def poll_once(self) -> int:
        """One tail step: pull changelog rows after our applied watermark
        and replay them. Returns rows applied; a source failure arms (and
        eventually fires) the promote-on-silence rule."""
        if self.promoted:
            return 0
        try:
            total = 0
            while True:
                rows = self.source.get_changelog(self.applied_seq, limit=500)
                # the primary ANSWERED: it is alive — stamp the liveness
                # clock here, before the local apply, so a transient
                # SQLITE_BUSY burst on the STANDBY's own write path can
                # never masquerade as primary silence and self-promote
                # into a split brain
                self._last_ok = time.monotonic()
                if not rows:
                    break
                if (min(r["epoch"] for r in rows)
                        < self.target.current_epoch()):
                    # the source's history is from an OLDER epoch than
                    # this store: this store promoted past it (e.g. a
                    # once-promoted standby re-attached to a rebuilt or
                    # zombie primary). Their seq spaces have diverged —
                    # applying would silently interleave two histories.
                    self.healthy = False
                    if not self._divergence_warned:
                        self._divergence_warned = True
                        print(
                            "[standby] REFUSING to tail: the source's "
                            f"changelog is at epoch "
                            f"{min(r['epoch'] for r in rows)} but this "
                            f"store is at epoch "
                            f"{self.target.current_epoch()} — histories "
                            "diverged; wipe this standby's db to "
                            "re-attach it", flush=True)
                    return 0
                self.target.apply_changelog(rows)
                self.applied_seq = max(self.applied_seq, rows[-1]["seq"])
                total += len(rows)
                if len(rows) < 500:
                    break
            span = self.source.changelog_span()
            self.source_seq = max(span.get("seq", 0), self.applied_seq)
            self.healthy = True
            return total
        except CompactedLogError as e:
            # the primary is ALIVE but our cursor fell below its
            # compaction floor: re-bootstrap territory, never promotion
            # territory — and never a silent skip of the pruned rows
            self._last_ok = time.monotonic()
            self.healthy = False
            if not self._compaction_warned:
                self._compaction_warned = True
                print(f"[standby] tail cursor compacted away ({e}); "
                      "re-bootstrap this standby from the primary's "
                      "snapshot", flush=True)
            return 0
        except ConnectionError:
            # unreachable (StoreUnavailableError subclasses this): the
            # ONLY failure class that counts toward primary silence
            self.healthy = False
            if (self.promote_after is not None and not self.promoted
                    and time.monotonic() - self._last_ok
                    >= self.promote_after):
                self.promote(reason="primary silent past promote_after")
            return 0
        except Exception as e:
            # the primary ANSWERED (4xx — e.g. a misconfigured auth
            # token) or the fault is local (standby-side apply weather):
            # either way the primary is not dead, and promoting off a
            # config error would be a split brain with a healthy primary.
            # Loud once: a standby silently replicating zero rows forever
            # is an operator trap
            self.healthy = False
            self._last_ok = time.monotonic()
            if not self._error_warned:
                self._error_warned = True
                print(f"[standby] replication erroring (source is alive, "
                      f"so NOT promoting): {e!r} — check auth/config; "
                      "this warning prints once", flush=True)
            return 0

    def promote(self, reason: str = "manual") -> int:
        """Promote the target to primary (idempotent): epoch bump + lease
        wipe in one transaction, read-only lifted, tailing stopped."""
        with self._lock:
            if not self.promoted:
                # epoch bump + read-only lift FIRST: ``promoted`` is the
                # flag harnesses/operators wait on, so it must only flip
                # once the target actually serves writes
                self.epoch = self.target.promote()
                self.promoted = True
                print(f"[standby] PROMOTED to primary at epoch "
                      f"{self.epoch} ({reason}; applied seq "
                      f"{self.applied_seq}, last known primary seq "
                      f"{self.source_seq})", flush=True)
        return self.epoch

    def start(self) -> "ReplicatedStandby":
        def _loop():
            while not self._stop.wait(self.poll_interval):
                if self.promoted:
                    return
                try:
                    self.poll_once()
                except Exception:
                    traceback.print_exc()

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="plx-standby-tail")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)


# -- the failover front ------------------------------------------------------


class FailoverStore:
    """An ordered list of store handles behind one store-shaped surface —
    the in-process twin of the client's multi-endpoint rotation.

    Every verb goes to the CURRENT handle; :class:`StoreUnavailableError`
    (or any :class:`ConnectionError`) rotates to the next and retries the
    call there, once per handle per call. Sticky: the rotation survives
    the call, so after a failover every caller is already pointed at the
    survivor. Deliberately NOT rotated on: transient sqlite weather
    (``database is locked`` — same host, retrying there is correct),
    fencing 409s / epoch 410s (terminal verdicts — identical on every
    replica), and a standby's read-only 503 (the primary is dead and the
    standby hasn't promoted yet: the caller must wait, not bounce).

    Transition listeners register on EVERY handle — the agent's change
    feed must keep waking it from whichever store is committing."""

    def __init__(self, stores: list, on_failover=None):
        if not stores:
            raise ValueError("FailoverStore needs at least one store")
        self._stores = list(stores)
        self._idx = 0
        self._rot_lock = threading.Lock()
        self._on_failover = on_failover

    @property
    def current(self):
        return self._stores[self._idx]

    @property
    def endpoints(self) -> list:
        return list(self._stores)

    def add_transition_listener(self, fn) -> None:
        for s in self._stores:
            s.add_transition_listener(fn)

    def _rotate(self, from_idx: int) -> None:
        with self._rot_lock:
            if self._idx == from_idx:
                self._idx = (from_idx + 1) % len(self._stores)
                if self._on_failover is not None:
                    try:
                        self._on_failover(self._idx)
                    except Exception:
                        traceback.print_exc()

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self.current, name)
        if not callable(attr):
            return attr

        def _call(*a: Any, _name=name, **kw: Any) -> Any:
            last: Optional[BaseException] = None
            for _ in range(len(self._stores)):
                idx = self._idx
                try:
                    return getattr(self._stores[idx], _name)(*a, **kw)
                except (StoreUnavailableError, ConnectionError) as e:
                    last = e
                    self._rotate(idx)
            raise last  # every endpoint unreachable: surface the weather

        _call.__name__ = name
        return _call


class ChangelogCompactor:
    """Periodic snapshot + changelog prune for a long-lived store
    (``snapshot_to`` on a timer): without it the changelog — one row per
    write, including heartbeats — grows without bound. Runs in every
    server deployment by default (``--compact-every``); each cycle also
    refreshes an on-disk snapshot standbys can bootstrap from. Safe on a
    demoted standby too (its own changelog mirror grows identically, and
    nothing tails a standby)."""

    def __init__(self, store: Store, dirpath: str,
                 interval: float = 900.0, keep: int = 10_000):
        self.store = store
        self.dirpath = dirpath
        self.interval = interval
        self.keep = keep
        self.cycles = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def compact_once(self) -> dict:
        manifest = snapshot_to(self.store, self.dirpath, keep=self.keep)
        self.cycles += 1
        return manifest

    def start(self) -> "ChangelogCompactor":
        def _loop():
            while not self._stop.wait(self.interval):
                try:
                    self.compact_once()
                except Exception:
                    traceback.print_exc()

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="plx-changelog-compactor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)


def make_standby(source_url: str, store: Store, data_dir: str,
                 promote_after: Optional[float] = None,
                 poll_interval: float = 0.5,
                 auth_token: Optional[str] = None) -> ReplicatedStandby:
    """One-call wiring for a warm-standby server process (the
    ``--standby-of`` flag in both ``polyaxon server`` and
    ``python -m polyaxon_tpu.api``): build the HTTP replication source,
    bootstrap an EMPTY local store from the primary's snapshot (a torn or
    unfetchable snapshot degrades to a full changelog tail, loudly), and
    return the standby — unstarted, so the caller controls the thread."""
    source = HttpReplicationSource(source_url, auth_token=auth_token)
    snap_dir = os.path.join(data_dir, ".standby-snapshot")
    standby = ReplicatedStandby(source, store, poll_interval=poll_interval,
                                promote_after=promote_after,
                                snapshot_dir=snap_dir)
    if store.current_seq() == 0:
        try:
            source.fetch_snapshot(snap_dir)
            standby.bootstrap()
        except Exception as e:
            print(f"[standby] snapshot bootstrap skipped ({e}); tailing "
                  "the full changelog", flush=True)
    return standby


def snapshot_to(store: Store, dirpath: str,
                keep: int = 10_000) -> dict:
    """Write a snapshot of ``store`` into ``dirpath`` and prune the
    changelog below the snapshot's seq minus a ``keep``-row safety margin
    — the compaction loop a long-lived primary runs so the changelog
    stays bounded. The pruned floor is RECORDED in the store: a standby
    whose tail cursor falls below it gets a loud
    :class:`~polyaxon_tpu.api.store.CompactedLogError` (re-bootstrap from
    the snapshot) instead of silently skipping the pruned writes. The
    default margin covers any standby within ~10k rows of the head;
    ``keep < 0`` disables pruning (snapshot only).

    A sharded store (ISSUE 18: it exposes ``.backends``) compacts per
    backend into ``shard-NN/`` subdirs — each shard keeps its OWN
    ``keep``-row tail and records its own floor, so a lagging tailer of
    the stitched feed 410s on exactly the shard component it fell behind
    on. ``ChangelogCompactor`` therefore works on either implementation
    unchanged."""
    backends = getattr(store, "backends", None)
    if backends is not None:
        manifests = [
            snapshot_to(b, os.path.join(dirpath, f"shard-{i:02d}"),
                        keep=keep)
            for i, b in enumerate(backends)]
        from .sharded_store import pack_seqs

        return {"num_shards": len(backends), "shards": manifests,
                "seq": pack_seqs([m["seq"] for m in manifests]),
                "epoch": sum(m.get("epoch", 0) for m in manifests)}
    manifest = store.snapshot(dirpath)
    if keep >= 0:
        floor = manifest["seq"] - keep
        if floor > 0:
            with store._conn_ctx() as conn:
                conn.execute("DELETE FROM changelog WHERE seq<=?", (floor,))
                conn.execute(
                    "UPDATE counters SET v=MAX(v, ?) "
                    "WHERE k='changelog_floor'", (floor,))
    return manifest


__all__ = [
    "ChangelogCompactor", "FailoverStore", "HttpReplicationSource",
    "ReplicatedStandby", "StoreUnavailableError", "TornSnapshotError",
    "make_standby", "restore_snapshot", "snapshot_to", "verify_snapshot",
]
