"""Concurrency-invariant analyzer engine (ISSUE 11 tentpole).

A custom AST-based static analyzer — stdlib ``ast`` only, mirroring the
hand-rolled-Prometheus philosophy of the obs layer — whose rules encode
the bug classes the control plane's post-review hardening rounds kept
re-discovering by hand (unfenced store writes, writer-thread
self-deadlocks, blocking calls wedging the event loop, wall-clock lease
arithmetic, metrics contract drift, donated-buffer reuse). Each rule
module under :mod:`polyaxon_tpu.analysis.rules` documents which PR's
hardening round it encodes; docs/ANALYSIS.md is the catalog.

The engine owns everything rule-agnostic:

- file discovery + parsing into a :class:`Project` of :class:`SourceFile`
  objects rules can walk;
- suppressions: ``# plx: allow(<rule>): <justification>`` on the flagged
  line (or the line directly above) marks a finding suppressed. The
  justification text is MANDATORY — an allow() without one is itself a
  finding (rule ``suppression``) and cannot be suppressed;
- machine-readable JSON (schema pinned by tests/test_analysis.py) and
  human output;
- the exit-code contract: 0 iff the tree has no unsuppressed findings.

Static analysis proposes, the chaos soak witnesses: the runtime
complement for the lock-order rule lives in
:mod:`polyaxon_tpu.analysis.lockwitness`.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable, Optional

#: suppression comments: ``# plx: allow(rule)`` or ``# plx: allow(a,b)``,
#: with the mandatory justification after a colon
_ALLOW_RE = re.compile(
    r"#\s*plx:\s*allow\(\s*([a-z0-9_,\s-]+?)\s*\)\s*(?::\s*(.*\S))?\s*$")

#: analyzer targets relative to the repo root — the LIVE tree the tier-1
#: tree-clean test gates on. tests/ stays out on purpose: the regression
#: corpus under tests/analysis_corpus/ reproduces each rule's historical
#: bug class and must keep flagging.
DEFAULT_TARGETS = ("polyaxon_tpu", "scripts")

JSON_SCHEMA_VERSION = 1


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # root-relative, '/'-separated
    line: int
    message: str
    col: int = 0
    suppressed: bool = False
    justification: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "justification": self.justification,
        }

    def render(self) -> str:
        sup = "  (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{sup}"


class SourceFile:
    """One parsed source file: text, lines, AST, and its suppression
    comments keyed by line number."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:  # surfaced as a finding by the engine
            self.parse_error = f"{e.msg} (line {e.lineno})"
        # line -> (set of rule names, justification or None)
        self.suppressions: dict[int, tuple[set, Optional[str]]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            self.suppressions[i] = (rules, m.group(2))

    def suppression_for(self, rule: str, line: int):
        """The allow() covering ``rule`` at ``line``: same line, or a
        comment on the line directly above the flagged one."""
        for ln in (line, line - 1):
            entry = self.suppressions.get(ln)
            if entry is not None and rule in entry[0]:
                return ln, entry[1]
        return None


class Project:
    """Every analyzed file plus cross-file indexes rules share."""

    def __init__(self, files: list[SourceFile], root: str):
        self.files = files
        self.root = root
        # class name -> (SourceFile, ClassDef); single namespace is fine
        # for this codebase (names are unique enough, collisions only
        # cost rule precision, never correctness of the build)
        self.classes: dict[str, tuple[SourceFile, ast.ClassDef]] = {}
        for sf in files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes.setdefault(node.name, (sf, node))

    def read_rootfile(self, *rel) -> Optional[str]:
        """Text of a file under the analysis root (None when absent) —
        how the metrics rule reaches tests/test_obs.py + docs/."""
        p = os.path.join(self.root, *rel)
        try:
            with open(p, encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None


class Rule:
    """Base class: subclasses set ``name``/``title`` and implement
    :meth:`check`."""

    name = "rule"
    title = ""

    def check(self, project: Project) -> list[Finding]:
        raise NotImplementedError


def default_rules() -> list[Rule]:
    from .rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def _discover(root: str, targets: Iterable[str]) -> list[str]:
    out = []
    for target in targets:
        p = os.path.join(root, target)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


@dataclasses.dataclass
class Report:
    root: str
    files_analyzed: int
    rules: list[str]
    findings: list[Finding]

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def exit_code(self) -> int:
        return 1 if self.active else 0

    def by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.active:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "version": JSON_SCHEMA_VERSION,
            "root": self.root,
            "files_analyzed": self.files_analyzed,
            "rules": list(self.rules),
            "findings": [f.to_dict() for f in sorted(
                self.findings, key=lambda f: (f.path, f.line, f.rule))],
            "summary": {
                "total": len(self.findings),
                "active": len(self.active),
                "suppressed": len(self.suppressed),
                "by_rule": self.by_rule(),
            },
        }

    def render_text(self) -> str:
        lines = []
        for f in sorted(self.findings,
                        key=lambda f: (f.path, f.line, f.rule)):
            lines.append(f.render())
        lines.append(
            f"analysis: {self.files_analyzed} files, "
            f"{len(self.active)} finding(s), "
            f"{len(self.suppressed)} suppressed")
        if self.active:
            lines.append("by rule: " + ", ".join(
                f"{k}={v}" for k, v in sorted(self.by_rule().items())))
        return "\n".join(lines)


def repo_root() -> str:
    """The repository root: the directory holding the polyaxon_tpu
    package this module was imported from."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def load_project(root: Optional[str] = None,
                 targets: Optional[Iterable[str]] = None) -> Project:
    root = os.path.abspath(root or repo_root())
    if targets is None:
        found = [t for t in DEFAULT_TARGETS
                 if os.path.exists(os.path.join(root, t))]
        targets = found or ["."]
    files = []
    for path in _discover(root, targets):
        rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8") as fh:
            files.append(SourceFile(path, rel, fh.read()))
    return Project(files, root)


def run_analysis(root: Optional[str] = None,
                 targets: Optional[Iterable[str]] = None,
                 rules: Optional[list[Rule]] = None) -> Report:
    """Analyze ``targets`` under ``root`` with ``rules`` (default: all).

    Suppression + justification processing happens here so rules stay
    pure detectors."""
    project = load_project(root, targets)
    rules = rules if rules is not None else default_rules()
    findings: list[Finding] = []
    for sf in project.files:
        if sf.parse_error is not None:
            findings.append(Finding(
                rule="parse", path=sf.rel, line=1,
                message=f"file does not parse: {sf.parse_error}"))
    for rule in rules:
        findings.extend(rule.check(project))
    by_rel = {sf.rel: sf for sf in project.files}
    out: list[Finding] = []
    for f in findings:
        sf = by_rel.get(f.path)
        entry = (sf.suppression_for(f.rule, f.line)
                 if sf is not None and f.rule != "suppression" else None)
        if entry is not None:
            ln, justification = entry
            if justification:
                f.suppressed = True
                f.justification = justification
            else:
                # an allow() with no written justification suppresses
                # nothing — and is itself reported, unsuppressibly
                out.append(Finding(
                    rule="suppression", path=f.path, line=ln,
                    message=f"plx: allow({f.rule}) needs a justification "
                            "(`# plx: allow(rule): why this is safe`)"))
        out.append(f)
    return Report(
        root=project.root,
        files_analyzed=len(project.files),
        rules=[r.name for r in rules],
        findings=out,
    )


def find_cycles(graph: dict, max_len: int = 8) -> list[list]:
    """Distinct elementary cycles in a small digraph ``{node: {succ}}``,
    each returned as a closed trail ``[a, b, ..., a]``. Shared between
    the static lockorder rule and the runtime LockWitness so the two
    verdicts can never drift. Cycles are deduped by node SET — adequate
    for lock graphs (any cycle at all is a finding), not a general
    elementary-circuit enumerator."""
    seen: set = set()
    out: list[list] = []

    def dfs(start, node, trail):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                key = frozenset(trail)
                if key not in seen:
                    seen.add(key)
                    out.append(trail + [start])
            elif nxt not in trail and len(trail) < max_len:
                dfs(start, nxt, trail + [nxt])

    for n in sorted(graph):
        dfs(n, n, [n])
    return out


# -- shared AST helpers (used by several rules) ------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.AST) -> dict[str, str]:
    """{local name: dotted module/object} from import statements —
    ``import time as _time`` maps ``_time -> time``; ``from time import
    sleep`` maps ``sleep -> time.sleep``."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def call_target(call: ast.Call, aliases: dict[str, str]) -> Optional[str]:
    """The dotted call target with import aliases resolved:
    ``_time.time()`` -> ``time.time``."""
    name = dotted_name(call.func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    resolved = aliases.get(head)
    if resolved:
        return f"{resolved}.{rest}" if rest else resolved
    return name
