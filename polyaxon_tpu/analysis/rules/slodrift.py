"""R8 — SLO/alert contract drift (ISSUE 20).

Bug-class provenance (PR 20): the SLO engine evaluates burn rates
against the history recorder, which only retains families on its
allowlist — so an SLO spec (or allowlist entry) naming a family that no
registration produces evaluates against permanent silence: burn 0,
alert never fires, and nothing errors. The drift is invisible at
runtime by construction (the "no data → burn 0" rule is deliberate:
a freshly started recorder must not page). The second half of the
contract is the fenced-verb list: the alert state machine's
exactly-once guarantee rests on ``upsert_alert``/``resolve_alert``
being fenced, so a file that defines those verbs next to a
``_FENCED`` tuple or ``WRITE_VERBS`` set that omits them has silently
opened the double-fire/double-resolve hole across agent takeovers.

Checks:

- every ``polyaxon_*`` family referenced by a ``*SLO_PACK*`` assignment
  (dict keys ``family``/``bad_family``/``total_family``, snake or
  camel) or a ``*ALLOWLIST*`` sequence assignment must be produced by
  some registration in the analyzed tree, or contracted in
  ``tests/test_obs.py``'s ``EXPECTED_FAMILIES``;
- a file that defines ``def upsert_alert`` / ``def resolve_alert`` and
  also assigns a ``_FENCED`` / ``WRITE_VERBS`` verb container must list
  those verbs in EVERY such container in that file.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Project, Rule
from .metrics_contract import (
    _REGISTER_ATTRS, _name_parts, _parse_expected_families, _Registration,
)

#: dict keys inside an SLO spec that name a metric family (BaseSchema
#: accepts both snake and camelCase on the wire)
_FAMILY_KEYS = frozenset({
    "family", "bad_family", "total_family", "badFamily", "totalFamily",
})

#: the fenced alert verbs (mirror of the ISSUE 20 FencedStore additions)
_ALERT_VERBS = ("upsert_alert", "resolve_alert")

#: assignment-target names that hold verb containers whose omission of
#: an alert verb is the exactly-once hole
_VERB_CONTAINERS = ("_FENCED", "WRITE_VERBS")


def _target_names(node: ast.Assign) -> list:
    out = []
    for t in node.targets:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, ast.Attribute):
            out.append(t.attr)
    return out


def _string_constants(node: ast.AST) -> list:
    """Every string literal under ``node``, with its AST node for
    location reporting."""
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


class SloDriftRule(Rule):
    name = "slodrift"
    title = "SLO spec / fenced alert verb contract drift"

    def check(self, project: Project) -> list[Finding]:
        regs = self._registrations(project)
        expected = _parse_expected_families(
            project.read_rootfile("tests", "test_obs.py"))
        out: list[Finding] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            out.extend(self._check_families(sf, regs, expected))
            out.extend(self._check_verbs(sf))
        return out

    def _registrations(self, project: Project) -> list:
        """Same scan as R5: every ``.counter/.gauge/.histogram`` call
        whose family literal starts ``polyaxon_`` (f-strings matched as
        wildcards)."""
        regs = []
        for sf in project.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _REGISTER_ATTRS
                        and node.args):
                    continue
                parts = _name_parts(node.args[0])
                if parts is None:
                    continue
                head = next((p for p in parts if p is not None), "")
                if not head.startswith("polyaxon_"):
                    continue
                regs.append(_Registration(
                    sf, node, _REGISTER_ATTRS[node.func.attr], parts))
        return regs

    def _family_refs(self, sf) -> list:
        """(family-string Constant node) references in SLO pack / history
        allowlist assignments."""
        refs = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = _target_names(node)
            if any("SLO_PACK" in n for n in names):
                for d in ast.walk(node.value):
                    if not isinstance(d, ast.Dict):
                        continue
                    for k, v in zip(d.keys, d.values):
                        if (isinstance(k, ast.Constant)
                                and k.value in _FAMILY_KEYS
                                and isinstance(v, ast.Constant)
                                and isinstance(v.value, str)):
                            refs.append(v)
            elif any("ALLOWLIST" in n for n in names):
                refs.extend(c for c in _string_constants(node.value)
                            if c.value.startswith("polyaxon_"))
        return refs

    def _check_families(self, sf, regs, expected) -> list[Finding]:
        out = []
        for ref in self._family_refs(sf):
            family = ref.value
            if not family.startswith("polyaxon_"):
                continue
            if family in expected or any(r.matches(family) for r in regs):
                continue
            out.append(Finding(
                rule=self.name, path=sf.rel, line=ref.lineno,
                col=ref.col_offset,
                message=(
                    f"SLO/allowlist references family {family!r} but no "
                    "registration produces it and EXPECTED_FAMILIES does "
                    "not contract it — the recorder would hold permanent "
                    "silence there, so burn stays 0 and the alert can "
                    "never fire"),
            ))
        return out

    def _check_verbs(self, sf) -> list[Finding]:
        defined = {node.name for node in ast.walk(sf.tree)
                   if isinstance(node, ast.FunctionDef)
                   and node.name in _ALERT_VERBS}
        if not defined:
            return []
        out = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            names = [n for n in _target_names(node)
                     if n in _VERB_CONTAINERS]
            if not names:
                continue
            listed = {c.value for c in _string_constants(node.value)}
            for verb in sorted(defined - listed):
                out.append(Finding(
                    rule=self.name, path=sf.rel, line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"alert verb {verb!r} is defined in this file "
                        f"but missing from {names[0]} — unfenced alert "
                        "transitions double-fire/double-resolve across "
                        "agent takeovers (exactly-once is the ISSUE 20 "
                        "contract)"),
                ))
        return out
