"""R1 — fence-bypass: unfenced store writes from control-plane drivers.

Bug-class provenance (PR 4/6 hardening rounds): every lifecycle write a
scheduling component makes must carry the writer's CURRENT lease fence,
or a stale incarnation keeps mutating runs a successor already owns. The
repo's design answer is the :class:`FencedStore` proxy — the agent wraps
the raw store once and hands THAT down to everything writing on its
behalf (reaper, pipeline drivers, executor callbacks), under the
canonical attribute name ``store``.

The rule enforces the discipline statically, in the driver modules
(``scheduler/``, ``operator/``, ``resilience/heartbeat.py``): a store
write verb may be called only

- on a receiver whose provenance is a ``FencedStore(...)`` construction
  (tracked through ``self.X = FencedStore(...)`` and local assignments),
- on the canonical handle (``self.store`` / bare ``store``) — the name
  the fenced proxy travels under; a class that binds ``self.store``
  directly from a raw ``Store(...)`` construction loses the exemption,
- or with an explicit ``fence=`` argument.

Writing through anything else — a raw ``Store(...)`` value, an
``_inner`` access that reaches around the proxy, a stashed raw reference
like ``_store_ref`` — is the historical bug.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Project, Rule, dotted_name

#: must stay a superset of FencedStore._FENCED (asserted in
#: tests/test_analysis.py so the two lists cannot drift apart)
WRITE_VERBS = frozenset({
    "create_run", "create_runs", "transition", "transition_many",
    "update_run", "merge_outputs", "record_launch_intent",
    "mark_launched", "adopt_launch", "annotate_status", "place_run",
    # sweep write-ahead windows (ISSUE 19): a trial intent or its
    # created-marker written without the pipeline shard's fence lets a
    # dead driver keep planting windows a successor already owns
    "record_trial_intents", "mark_trials_created",
    # SLO alert state machine (ISSUE 20): an alert transition written
    # without the owning agent's fence double-fires / double-resolves
    # across takeovers — exactly-once is the whole contract
    "upsert_alert", "resolve_alert",
})

#: root-relative path prefixes where the discipline applies — the
#: modules that drive run lifecycles on an agent's behalf
SCOPE_PREFIXES = ("scheduler/", "operator/", "resilience/heartbeat.py",
                  # the sweep driver launches trial runs (ISSUE 19)
                  "hypertune/")

#: receivers trusted by convention: the fenced proxy's canonical names
CANONICAL = ("self.store", "store")


def _in_scope(rel: str) -> bool:
    # both the package layout (polyaxon_tpu/scheduler/...) and the
    # corpus layout (scheduler/...) must match
    rel = rel.split("polyaxon_tpu/", 1)[-1]
    return rel.startswith(SCOPE_PREFIXES)


class _ClassInfo(ast.NodeVisitor):
    """Provenance of ``self.X`` attributes and locals within one class:
    which names hold a FencedStore, which hold a raw Store."""

    def __init__(self):
        self.fenced: set[str] = set()   # "self.x" / "x"
        self.raw: set[str] = set()

    def classify(self, target: str, value: ast.AST) -> None:
        if not isinstance(value, ast.Call):
            return
        ctor = dotted_name(value.func) or ""
        tail = ctor.rsplit(".", 1)[-1]
        if tail == "FencedStore":
            self.fenced.add(target)
        elif tail in ("Store", "FaultyStore", "OutageStore"):
            self.raw.add(target)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            name = dotted_name(t)
            if name is not None:
                self.classify(name, node.value)
        self.generic_visit(node)


def _walk_pruning_classes(node):
    """ast.walk that does NOT descend into nested ClassDefs — each class
    is analyzed with its own _ClassInfo; re-walking its body from the
    module scope would double-report and lose the class's provenance."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.ClassDef):
            continue
        yield child
        yield from _walk_pruning_classes(child)


class FenceRule(Rule):
    name = "fence"
    title = "store writes from driver modules must be fenced"

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for sf in project.files:
            if sf.tree is None or not _in_scope(sf.rel):
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    info = _ClassInfo()
                    info.visit(node)
                    self._check_scope(sf, node, info, out)
            # module-level / function-level code outside classes (class
            # bodies pruned: they were just checked with their own info)
            info = _ClassInfo()
            for node in sf.tree.body:
                if not isinstance(node, ast.ClassDef):
                    info.visit(node)
            self._check_scope(sf, sf.tree, info, out, skip_classes=True)
        return out

    def _check_scope(self, sf, scope, info: _ClassInfo,
                     out: list[Finding], skip_classes: bool = False) -> None:
        # prune nested ClassDefs in BOTH passes: every class is checked
        # exactly once, with its own provenance info
        for node in _walk_pruning_classes(scope):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in WRITE_VERBS:
                continue
            if any(kw.arg == "fence" for kw in node.keywords):
                continue
            recv = dotted_name(func.value)
            if recv is not None:
                if recv in info.fenced:
                    continue
                if "_inner" in recv.split("."):
                    pass  # reaching around the proxy: always flagged
                elif recv in info.raw:
                    pass  # raw Store provenance: flagged
                elif recv in CANONICAL:
                    continue  # the fenced handle's canonical name
                elif recv.startswith("self.") and skip_classes:
                    continue  # free function on an unknown object
            else:
                # chained construction: Store(...).transition(...)
                inner = func.value
                ctor = (dotted_name(inner.func)
                        if isinstance(inner, ast.Call) else None)
                if ctor is None or not ctor.endswith("Store"):
                    continue
                if ctor.rsplit(".", 1)[-1] == "FencedStore":
                    continue
                recv = ctor + "(...)"
            out.append(Finding(
                rule=self.name, path=sf.rel, line=node.lineno,
                col=node.col_offset,
                message=(
                    f"unfenced store write: {recv}.{func.attr}(...) in a "
                    "driver module bypasses the FencedStore proxy — write "
                    "through the agent's fenced `store` handle or pass "
                    "fence= explicitly"),
            ))
