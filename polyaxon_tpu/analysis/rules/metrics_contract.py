"""R5 — metrics contract: naming, typing, and drift against the tests.

Bug-class provenance (PR 5/7 hardening rounds): the chaos-injected
counter was registered as a Gauge (monotonic + ``_total`` but not
counter-typed — ``rate()`` over it is wrong), and later PRs kept
catching families that landed in code but never in
``test_obs.EXPECTED_FAMILIES`` or docs/OBSERVABILITY.md — contract
drift a reviewer has to notice by reading three files at once. This
rule reads all three.

Per registration site (any ``.counter("polyaxon_...")`` /
``.gauge(...)`` / ``.histogram(...)`` call whose family-name literal
starts with ``polyaxon_``):

- names are snake_case;
- a family ending ``_total`` must be a Counter, and a Counter must end
  ``_total`` (the Prometheus monotonicity convention ``rate()`` relies
  on);
- histograms carry a unit suffix (``_seconds`` today);
- live-tree only (when tests/test_obs.py + docs/OBSERVABILITY.md exist
  under the analysis root): every literal family must appear in
  docs/OBSERVABILITY.md, and every family contracted in
  ``EXPECTED_FAMILIES`` must still be registered somewhere (a renamed
  family with a stale test contract is exactly the drift PR 7 shipped).
  f-string registrations (the store's ``stats`` export loop) are
  checked on their literal parts and matched as wildcards.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ..engine import Finding, Project, Rule

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_REGISTER_ATTRS = {"counter": "counter", "gauge": "gauge",
                   "histogram": "histogram"}
_HIST_UNITS = ("_seconds", "_bytes", "_ratio")


def _name_parts(node: ast.AST) -> Optional[list]:
    """The family-name argument as [literal or None, ...] pieces; None
    for non-string args."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.JoinedStr):
        out = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.append(v.value)
            else:
                out.append(None)
        return out
    return None


class _Registration:
    def __init__(self, sf, node, mtype, parts):
        self.sf, self.node, self.mtype, self.parts = sf, node, mtype, parts
        self.literal = ("".join(parts) if None not in parts else None)

    @property
    def display(self) -> str:
        return self.literal or "".join(
            p if p is not None else "{…}" for p in self.parts)

    def matches(self, family: str) -> bool:
        """Whether this registration can produce ``family`` (wildcard
        match for f-strings)."""
        if self.literal is not None:
            return self.literal == family
        pat = "".join(re.escape(p) if p is not None else ".+"
                      for p in self.parts)
        return re.fullmatch(pat, family) is not None


class MetricsContractRule(Rule):
    name = "metrics"
    title = "Prometheus family naming/typing/contract consistency"

    def check(self, project: Project) -> list[Finding]:
        regs: list[_Registration] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _REGISTER_ATTRS
                        and node.args):
                    continue
                parts = _name_parts(node.args[0])
                if parts is None:
                    continue
                head = next((p for p in parts if p is not None), "")
                if not head.startswith("polyaxon_"):
                    continue
                regs.append(_Registration(
                    sf, node, _REGISTER_ATTRS[node.func.attr], parts))

        out: list[Finding] = []
        for r in regs:
            out.extend(self._check_shape(r))
        out.extend(self._check_drift(project, regs))
        return out

    def _check_shape(self, r: _Registration) -> list[Finding]:
        out = []
        name = r.literal
        if name is not None and not _SNAKE.match(name):
            out.append(self._f(r, f"family {name!r} is not snake_case"))
        # suffix/type contract works on the literal TAIL even for
        # f-strings (the store loop's `_total` suffix is literal)
        tail = r.parts[-1] if r.parts[-1] is not None else ""
        head_known = r.literal is not None
        if tail.endswith("_total") and r.mtype != "counter":
            out.append(self._f(
                r, f"family {r.display!r} ends _total (monotonic by "
                   f"convention) but is registered as a {r.mtype} — "
                   "rate()/increase() need a counter-typed family"))
        if head_known and r.mtype == "counter" \
                and not name.endswith("_total"):
            out.append(self._f(
                r, f"counter family {name!r} must end _total"))
        if head_known and r.mtype == "histogram" \
                and not name.endswith(_HIST_UNITS):
            out.append(self._f(
                r, f"histogram family {name!r} carries no unit suffix "
                   f"(expected one of {', '.join(_HIST_UNITS)})"))
        return out

    def _check_drift(self, project: Project,
                     regs: list[_Registration]) -> list[Finding]:
        """Cross-file contract checks — live tree only."""
        out: list[Finding] = []
        docs = project.read_rootfile("docs", "OBSERVABILITY.md")
        test_obs = project.read_rootfile("tests", "test_obs.py")
        if docs is not None:
            for r in regs:
                if "/analysis_corpus/" in r.sf.path:
                    continue
                if r.literal is not None and r.literal not in docs:
                    out.append(self._f(
                        r, f"family {r.literal!r} is registered but not "
                           "documented in docs/OBSERVABILITY.md"))
        expected = _parse_expected_families(test_obs)
        if expected:
            for family in sorted(expected):
                if not any(r.matches(family) for r in regs):
                    out.append(Finding(
                        rule=self.name, path="tests/test_obs.py", line=1,
                        message=(
                            f"EXPECTED_FAMILIES contracts {family!r} but "
                            "no registration produces it — the family was "
                            "renamed or removed without updating the "
                            "contract"),
                    ))
        return out

    def _f(self, r: _Registration, msg: str) -> Finding:
        return Finding(rule=self.name, path=r.sf.rel,
                       line=r.node.lineno, col=r.node.col_offset,
                       message=msg)


def _parse_expected_families(text: Optional[str]) -> set:
    """The EXPECTED_FAMILIES set literal out of tests/test_obs.py."""
    if text is None:
        return set()
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "EXPECTED_FAMILIES"
                for t in node.targets):
            try:
                value = ast.literal_eval(node.value)
            except ValueError:
                return set()
            if isinstance(value, (set, list, tuple)):
                return {v for v in value if isinstance(v, str)}
    return set()
