"""R3 — blocking calls on the event loop.

Bug-class provenance (PR 7 hardening, "GET /store/snapshot wedged the
loop"): ``Store.snapshot`` is O(whole database); running it inline in an
async handler silenced ``/api/v1/changelog`` long enough to trip an
attached standby's promote-on-silence rule — a false failover caused by
a blocked event loop, not a dead primary. The fix routed it through
``run_in_executor``; this rule keeps the class extinct.

The rule flags calls from a contracted *blocking set* made directly in
``async def`` bodies, anywhere in the tree (api/app.py and
serve/server.py are where the loop lives today, but the discipline is
universal). Code inside a nested **sync** ``def`` or ``lambda`` is
exempt: that is exactly the executor-shipping idiom
(``run_in_executor(None, _make)``) the fix introduced — the nested
function runs on a worker thread, not the loop.

The blocking set is deliberately contracted (sleep / subprocess /
sqlite / fsync / sync-HTTP / store snapshot-class calls), not "anything
that touches a file": flagging every small artifact read would bury the
O(database) findings this rule exists for. Extend ``BLOCKING_CALLS``
when a new class bites.
"""

from __future__ import annotations

import ast

from ..engine import (Finding, Project, Rule, call_target, dotted_name,
                      import_aliases)

#: resolved dotted call targets that block the calling thread
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "os.fsync", "os.sync",
    "sqlite3.connect",
    "socket.create_connection",
    "shutil.rmtree", "shutil.copy", "shutil.copy2", "shutil.copyfile",
    "shutil.copytree", "shutil.move",
})

#: any call into these modules blocks (sync HTTP, child processes)
BLOCKING_MODULES = ("subprocess", "requests", "urllib.request")

#: store verbs that are O(whole database): blocking on any receiver
#: whose dotted name mentions a store
STORE_HEAVY_VERBS = ("snapshot", "snapshot_to", "compact_changelog")


def _blocking_reason(call: ast.Call, aliases: dict) -> str | None:
    target = call_target(call, aliases)
    if target is not None:
        if target in BLOCKING_CALLS:
            return f"{target}() blocks the event loop"
        head = target.split(".")[0]
        if head in BLOCKING_MODULES or target.rsplit(".", 1)[0] in \
                BLOCKING_MODULES:
            return f"{target}() is synchronous ({head}) and blocks the loop"
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in STORE_HEAVY_VERBS:
        recv = dotted_name(call.func.value) or ""
        if "store" in recv.lower():
            return (f"{recv}.{call.func.attr}() is O(whole database) — "
                    "the PR-7 blocked-loop false-promotion class")
    return None


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walk one async def's own body: nested sync defs/lambdas are the
    executor idiom and are skipped; nested async defs are visited as
    loop code too."""

    def __init__(self, rule, sf, aliases, out):
        self.rule, self.sf, self.aliases, self.out = rule, sf, aliases, out

    def visit_FunctionDef(self, node):
        return  # sync nested def: shipped to an executor, off the loop

    def visit_AsyncFunctionDef(self, node):
        return  # visited by the module-level walk in its own right

    def visit_Lambda(self, node):
        return

    def visit_Call(self, node: ast.Call):
        reason = _blocking_reason(node, self.aliases)
        if reason is not None:
            self.out.append(Finding(
                rule=self.rule.name, path=self.sf.rel, line=node.lineno,
                col=node.col_offset,
                message=(f"blocking call in async def: {reason}; route it "
                         "through run_in_executor"),
            ))
        self.generic_visit(node)


class BlockingAsyncRule(Rule):
    name = "asyncblock"
    title = "no blocking calls directly on the event loop"

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    v = _AsyncBodyVisitor(self, sf, aliases, out)
                    for stmt in node.body:
                        v.visit(stmt)
        return out
