"""Rule registry — one module per bug class (docs/ANALYSIS.md is the
catalog with per-rule provenance)."""

from .blocking_async import BlockingAsyncRule
from .clock import ClockRule
from .crossshard import CrossShardRule
from .donation import DonationRule
from .fence import FenceRule
from .lockorder import LockOrderRule
from .metrics_contract import MetricsContractRule
from .slodrift import SloDriftRule

ALL_RULES = (
    FenceRule,          # R1 — unfenced store writes (PR 4/6)
    LockOrderRule,      # R2 — lock-order cycles / self-deadlock (PR 6)
    BlockingAsyncRule,  # R3 — blocking the event loop (PR 7)
    ClockRule,          # R4 — wall clock in lease arithmetic (PR 1/4)
    MetricsContractRule,  # R5 — metrics contract drift (PR 5/7)
    DonationRule,       # R6 — donated-buffer reuse (PR 8)
    CrossShardRule,     # R7 — cross-shard verb in a held shard txn (PR 18)
    SloDriftRule,       # R8 — SLO/alert contract drift (PR 20)
)

__all__ = ["ALL_RULES", "FenceRule", "LockOrderRule", "BlockingAsyncRule",
           "ClockRule", "MetricsContractRule", "DonationRule",
           "CrossShardRule", "SloDriftRule"]
