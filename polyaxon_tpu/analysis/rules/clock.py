"""R4 — clock discipline: wall clock is forbidden in control-plane code.

Bug-class provenance (PR 1's de-flaking round onward, re-audited every
PR since leases landed in PR 4): lease TTLs, renewal deadlines, zombie
windows, watchdog stalls and freshness horizons are all *durations on
one machine* — ``time.time()`` arithmetic there is wrong twice over
(NTP steps move it backwards; leap smearing stretches it), and the
failure is a false demotion or a false zombie reap under exactly the
conditions a chaos soak creates. ``time.monotonic()`` is the contract.

The rule inverts the usual lint default: inside the control-plane
modules (``api/``, ``scheduler/``, ``operator/``, ``resilience/``, plus
the serve engine and the train watchdog — the module set where every
timestamp is lease/TTL/deadline-adjacent) EVERY ``time.time()`` /
``datetime.now()`` call is a finding unless it carries a written
justification. Legitimate wall-clock uses exist — timestamps persisted
for humans (run meta, span clocks correlated across machines, file
mtimes) — and each one is exactly what the suppression syntax is for:

    meta["at"] = time.time()  # plx: allow(clock): persisted for humans

so the exemption is visible, justified, and reviewed at the call site
instead of silently ambient.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Project, Rule, call_target, import_aliases

#: control-plane scope: path prefixes (after stripping the package dir).
#: serve/ joined in ISSUE 12: decode deadlines, drain windows, Retry-After
#: derivations and watchdog stalls are all duration arithmetic — an NTP
#: step must not cancel a request early or fire a serving stall.
#: api/stream.py (ISSUE 14) rides the api/ prefix: SSE keepalive windows
#: and eviction write deadlines are durations too — an NTP step must not
#: evict a healthy watcher (scope pinned by test_analysis).
#: tenancy/ joined in ISSUE 15: token-bucket refill arithmetic and the
#: agent's quota-refresh TTL are durations — a wall-clock bucket would
#: mint (or confiscate) a burst of admission tokens on every NTP step
#: (corpus pair: analysis_corpus/tenancy/r15_*).
#: federation/ joined in ISSUE 16: cluster-health staleness and failover
#: gating are TTL-lease durations — a wall-clock health check would
#: declare a live cluster lost (and re-place its running work) on an NTP
#: step backwards (corpus pair: analysis_corpus/federation/r16_*).
SCOPE_PREFIXES = ("api/", "scheduler/", "operator/", "resilience/",
                  "serve/", "tenancy/", "federation/")
#: plus individual clock-sensitive modules outside those trees
SCOPE_FILES = ("train/watchdog.py",)

#: resolved call targets that read the wall clock
WALL_CLOCK = frozenset({
    "time.time",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})


def _in_scope(rel: str) -> bool:
    rel = rel.split("polyaxon_tpu/", 1)[-1]
    return rel.startswith(SCOPE_PREFIXES) or rel in SCOPE_FILES


class ClockRule(Rule):
    name = "clock"
    title = "monotonic clocks in lease/TTL/deadline arithmetic"

    def check(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for sf in project.files:
            if sf.tree is None or not _in_scope(sf.rel):
                continue
            aliases = import_aliases(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                target = call_target(node, aliases)
                if target in WALL_CLOCK:
                    out.append(Finding(
                        rule=self.name, path=sf.rel, line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"wall clock ({target}()) in a control-plane "
                            "module: lease/TTL/deadline arithmetic must "
                            "use time.monotonic(); persisted human-facing "
                            "timestamps need an inline justification "
                            "(`# plx: allow(clock): ...`)"),
                    ))
        return out
