"""R2 — lock-order cycles and self-deadlocks in the static lock graph.

Bug-class provenance (PR 6 hardening, "demotion self-deadlock"): a
FencedStore ``on_stale`` callback fired on a writer thread that already
held the agent lock, and the demotion bookkeeping tried to take the same
non-reentrant lock again — a self-deadlock only reachable under a
takeover race. The fix (two-phase demotion) is exactly the discipline
this rule checks: never *acquire* a lock on a path that may already hold
it, and never acquire two locks in opposite orders on two paths.

Construction of the graph, per class (plus module-level locks):

- lock attributes are ``self.X = threading.Lock()/RLock()/Condition()``
  assignments (module-level: ``X = threading.Lock()``);
- every ``with self.X:`` block contributes edges ``X -> Y`` for each
  lock ``Y`` acquired inside the block — directly, or transitively
  through calls the block makes (``self.m()`` same-class methods,
  ``self.attr.m()`` where ``self.attr = SomeClass(...)`` resolves to an
  analyzed class, and module-level functions);
- a non-reentrant lock reachable from inside its own hold is a
  self-deadlock finding; a cycle among distinct locks is a lock-order
  finding (reported once per cycle, at its first edge's site);
- ``KNOWN_BAD_ORDERS`` pins orders that are forbidden even without the
  reverse edge in today's tree — the PR-6 class (store writer lock held
  while reaching for the agent loop lock) must never come back.

Known blind spot (why the runtime witness exists): calls that cross the
``FencedStore`` proxy's dynamic ``__getattr__`` dispatch, callbacks
stored in variables, and cross-process lock interactions are invisible
statically. ``analysis.lockwitness.LockWitness`` records the ACTUAL
cross-thread acquisition orders during the chaos soaks and fails them on
a cycle — static analysis proposes, the soak witnesses.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..engine import Finding, Project, Rule, dotted_name

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

#: (holding, acquiring) qualified-name suffix pairs that are findings
#: even without a reverse edge — each encodes a historical deadlock
KNOWN_BAD_ORDERS = (
    # PR-6 demotion class: the store's writer lock is held across every
    # transition batch; reaching for the agent's loop lock from inside it
    # (e.g. a transition listener taking agent state) inverts the only
    # sanctioned order (agent lock -> store write) and deadlocks with any
    # pass that writes while holding the agent lock.
    ("Store._transition_lock", "LocalAgent._lock"),
)


class _ClassGraph:
    """Locks, methods, and attr->class typing for one class (or the
    module pseudo-class for top-level functions/locks)."""

    def __init__(self, qual: str):
        self.qual = qual
        self.locks: dict[str, str] = {}       # attr/name -> kind
        self.methods: dict[str, ast.AST] = {}
        self.attr_types: dict[str, str] = {}  # attr -> class name


def _scan_class(qual: str, body: list, is_module: bool) -> _ClassGraph:
    g = _ClassGraph(qual)
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            g.methods[node.name] = node
            if not is_module:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        _classify_assign(g, sub, "self.")
        elif isinstance(node, ast.Assign):
            _classify_assign(g, node, "" if is_module else "self.")
    return g


def _classify_assign(g: _ClassGraph, node: ast.Assign, prefix: str) -> None:
    if not isinstance(node.value, ast.Call):
        return
    ctor = dotted_name(node.value.func) or ""
    tail = ctor.rsplit(".", 1)[-1]
    for t in node.targets:
        name = dotted_name(t)
        if name is None:
            continue
        if prefix and not name.startswith(prefix):
            continue
        short = name[len(prefix):]
        if "." in short:
            continue
        if tail in _LOCK_CTORS and ("threading" in ctor
                                    or ctor == tail):
            g.locks[short] = _LOCK_CTORS[tail]
        elif tail and tail[0].isupper():
            g.attr_types[short] = tail


def _lock_of(expr: ast.AST, g: _ClassGraph) -> Optional[str]:
    """The lock attr name when ``expr`` is ``self.X``/(module) ``X`` for
    a known lock of this scope, else None."""
    name = dotted_name(expr)
    if name is None:
        return None
    if name.startswith("self."):
        name = name[5:]
    return name if name in g.locks else None


def _walk_same_context(node):
    """``node`` and its descendants, EXCLUDING nested function/lambda/
    class bodies: a closure built under a lock runs later (typically on
    another thread after release) — treating its acquisitions as
    happening inside the hold fabricates self-deadlocks. Deferred
    closures are the runtime witness's territory."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda, ast.ClassDef)):
        return  # a def statement only BINDS the closure; nothing runs
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _walk_same_context(child)


def _explicit_acquire(call: ast.Call, g: _ClassGraph) -> Optional[str]:
    """``self.X.acquire()`` on a known lock — an acquisition point for
    edge purposes (held-state past the call is not tracked; the runtime
    witness owns acquire/release flow)."""
    if isinstance(call.func, ast.Attribute) and call.func.attr == "acquire":
        return _lock_of(call.func.value, g)
    return None


class _Site:
    __slots__ = ("rel", "line", "path")

    def __init__(self, rel: str, line: int, path: list[str]):
        self.rel, self.line, self.path = rel, line, path


def _render(node: tuple) -> str:
    """(file, class, lock) -> "Class.lock" for human messages."""
    return f"{node[1]}.{node[2]}"


class LockOrderRule(Rule):
    name = "lockorder"
    title = "static lock-acquisition graph: cycles / self-deadlocks"

    def check(self, project: Project) -> list[Finding]:
        # graphs are keyed by (file, class) — same-named classes in two
        # files must not merge (their edges would fabricate cycles)
        graphs: dict[tuple, _ClassGraph] = {}
        name_index: dict[str, list] = {}
        for sf in project.files:
            if sf.tree is None:
                continue
            mod = sf.rel.rsplit("/", 1)[-1][:-3]
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    key = (sf.rel, node.name)
                    graphs[key] = _scan_class(
                        node.name, node.body, is_module=False)
                    name_index.setdefault(node.name, []).append(key)
            g = _scan_class(mod, sf.tree.body, is_module=True)
            if g.locks or g.methods:
                key = (sf.rel, mod)
                graphs.setdefault(key, g)
                name_index.setdefault(mod, []).append(key)

        self._graphs = graphs
        self._name_index = name_index
        self._memo: dict[tuple, dict] = {}

        # edges: (held_node, acquired_node) -> _Site; nodes are
        # (file, class, lock) tuples rendered as "Class.lock"
        edges: dict[tuple, _Site] = {}
        findings: list[Finding] = []
        for key, g in graphs.items():
            rel, cls = key
            for mname, mnode in g.methods.items():
                for w in ast.walk(mnode):
                    if not isinstance(w, ast.With):
                        continue
                    held = [_lock_of(item.context_expr, g)
                            for item in w.items]
                    held = [h for h in held if h is not None]
                    if not held:
                        continue
                    # multi-item with: left acquires before right
                    for i in range(len(held) - 1):
                        edges.setdefault(
                            (key + (held[i],), key + (held[i + 1],)),
                            _Site(rel, w.lineno, []))
                    inner = self._reachable_in_body(
                        key, w.body, [f"{cls}.{mname}"])
                    for h in held:
                        hq = key + (h,)
                        for acq, (line, path) in inner.items():
                            if acq == hq:
                                if g.locks[h] != "lock":
                                    continue  # reentrant: safe to re-take
                                findings.append(Finding(
                                    rule=self.name, path=rel, line=w.lineno,
                                    message=(
                                        f"self-deadlock: non-reentrant "
                                        f"{_render(hq)} is re-acquired "
                                        f"while held "
                                        f"(via {' -> '.join(path)})"),
                                ))
                                continue
                            edges.setdefault(
                                (hq, acq), _Site(rel, line, path))

        findings.extend(self._known_bad(edges))
        findings.extend(self._cycles(edges))
        return findings

    # -- reachability ------------------------------------------------------

    def _reachable_in_body(self, key: tuple, body: list,
                           path: list[str]) -> dict:
        """Locks acquired anywhere inside ``body`` (a with-block), keyed
        by lock node (file, class, lock) -> (line, call path)."""
        out: dict[tuple, tuple] = {}
        g = self._graphs[key]
        for node in body:
            for sub in _walk_same_context(node):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        lk = _lock_of(item.context_expr, g)
                        if lk is not None:
                            out.setdefault(
                                key + (lk,), (sub.lineno, list(path)))
                elif isinstance(sub, ast.Call):
                    lk = _explicit_acquire(sub, g)
                    if lk is not None:
                        out.setdefault(
                            key + (lk,), (sub.lineno, list(path)))
                    for tgt in self._resolve_call(key, sub):
                        for acq, pp in self._method_locks(*tgt).items():
                            out.setdefault(
                                acq, (sub.lineno, list(path) + pp))
        return out

    def _resolve_key(self, name: str, near: tuple) -> Optional[tuple]:
        """A class name -> graph key, preferring the same file as
        ``near`` (same-named classes in other files stay distinct)."""
        keys = self._name_index.get(name)
        if not keys:
            return None
        for k in keys:
            if k[0] == near[0]:
                return k
        return keys[0]

    def _resolve_call(self, key: tuple, call: ast.Call) -> list[tuple]:
        """Resolve a call inside graph ``key`` to [(key, method)]."""
        name = dotted_name(call.func)
        if name is None:
            return []
        g = self._graphs[key]
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2:
            if parts[1] in g.methods:
                return [(key, parts[1])]
            return []
        if parts[0] == "self" and len(parts) == 3:
            tk = self._resolve_key(g.attr_types.get(parts[1], ""), key)
            if tk is not None and parts[2] in self._graphs[tk].methods:
                return [(tk, parts[2])]
            return []
        if len(parts) == 1 and parts[0] in g.methods:
            # module-level function calling a sibling module function
            return [(key, parts[0])]
        return []

    def _method_locks(self, key: tuple, method: str,
                      _stack: Optional[frozenset] = None) -> dict:
        """Every lock acquired anywhere in (key, method), transitively
        through resolvable calls: lock node -> call path (frames)."""
        mkey = (key, method)
        if mkey in self._memo:
            return self._memo[mkey]
        stack = _stack or frozenset()
        if mkey in stack:
            return {}
        stack = stack | {mkey}
        g = self._graphs[key]
        node = g.methods[method]
        frame = f"{key[1]}.{method}"
        out: dict[tuple, list] = {}
        # walk the method's own execution context only: a nested def's
        # acquisitions happen when IT runs, not when this method does
        for stmt in node.body:
            self._scan_exec_context(stmt, key, g, frame, out, stack)
        if _stack is None:
            self._memo[mkey] = out
        return out

    def _scan_exec_context(self, root, key, g, frame, out, stack) -> None:
        for sub in _walk_same_context(root):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lk = _lock_of(item.context_expr, g)
                    if lk is not None:
                        out.setdefault(key + (lk,), [frame])
            elif isinstance(sub, ast.Call):
                lk = _explicit_acquire(sub, g)
                if lk is not None:
                    out.setdefault(key + (lk,), [frame])
                for tk, tm in self._resolve_call(key, sub):
                    sub_locks = self._method_locks(tk, tm, stack)
                    for acq, pp in sub_locks.items():
                        out.setdefault(acq, [frame] + pp)

    # -- graph verdicts ----------------------------------------------------

    def _known_bad(self, edges: dict) -> list[Finding]:
        out = []
        for (a, b), site in sorted(edges.items()):
            for bad_a, bad_b in KNOWN_BAD_ORDERS:
                if _render(a) == bad_a and _render(b) == bad_b:
                    out.append(Finding(
                        rule=self.name, path=site.rel, line=site.line,
                        message=(
                            f"forbidden lock order: {_render(a)} held "
                            f"while acquiring {_render(b)} "
                            f"(via {' -> '.join(site.path) or 'direct'}) — "
                            "the PR-6 demotion-deadlock class"),
                    ))
        return out

    def _cycles(self, edges: dict) -> list[Finding]:
        from ..engine import find_cycles

        graph: dict[tuple, set] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        out = []
        for trail in find_cycles(graph):
            if len(trail) <= 2:
                continue  # self-loops are the self-deadlock finding
            first = min(
                (e for e in zip(trail, trail[1:]) if e in edges),
                key=lambda e: (edges[e].rel, edges[e].line))
            site = edges[first]
            out.append(Finding(
                rule=self.name, path=site.rel, line=site.line,
                message=("lock-order cycle: "
                         + " -> ".join(_render(n) for n in trail)),
            ))
        return out
