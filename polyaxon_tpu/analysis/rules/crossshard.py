"""R7 — cross-shard store verbs inside another shard's transaction scope.

Bug-class provenance (ISSUE 18, sharded store): the run space is
partitioned over K independent SQLite backends, each with its own writer
lock and its own ``_conn_ctx()`` write transaction. The moment two
shards exist, a new hazard class exists with them: code that opens shard
A's transaction and then — while A's writer lock is held — reaches into
shard B (a nested ``B._conn_ctx()``, or any routed store verb on B,
which opens B's transaction internally). Two such paths with opposite
shard orders deadlock exactly like the PR-6 lock-order class, except the
"locks" are per-shard SQLite writer locks the static lock graph (R2)
cannot see — they live behind sqlite3, not ``threading``. Even a single
such path is a correctness smell: the outer shard's transaction is
neither isolated from nor atomic with the inner one, so a crash between
the two commits splits what the author thought was one atomic step
(why ``ShardedStore._split_fence`` documents verify-then-strip as
explicitly non-atomic and keeps the cross-shard read OUTSIDE the target
shard's transaction).

The discipline this rule enforces: finish (or never start) shard A's
transaction before touching shard B. Route first, then transact —
per-shard sub-batches each open exactly one backend's transaction.

Detection, per ``with <X>._conn_ctx()`` block (module- and class-level,
same execution context only — nested defs/lambdas run later, typically
after release):

- a nested ``with <Y>._conn_ctx()`` where ``Y`` is not syntactically the
  same receiver as ``X`` is a finding (holding one shard's writer lock
  while opening another's);
- a call ``<Y>.<verb>(...)`` where ``verb`` is a store verb that opens
  its own transaction and ``Y`` differs from ``X`` is a finding (the
  verb will open ``Y``'s transaction under ``X``'s lock).

Receivers are compared by their unparsed source text: ``self`` ==
``self``, ``home`` != ``target``, ``self._shards[i]`` !=
``self._shards[j]``. Two spellings of the same object (aliasing) are
invisible — like R2, this rule proposes and the chaos soak witnesses.
Same-receiver calls stay allowed: a store method calling its own
helpers inside its own transaction is the normal single-shard shape.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..engine import Finding, Project, Rule

#: store verbs that open their OWN write/read transaction when called —
#: invoking one on shard B while holding shard A's ``_conn_ctx`` nests
#: B's transaction under A's writer lock
STORE_VERBS = frozenset({
    "create_run", "create_runs", "transition", "transition_many",
    "update_run", "merge_outputs", "heartbeat", "delete_run",
    "get_run", "get_runs", "list_runs", "count_runs", "get_statuses",
    "acquire_lease", "renew_lease", "renew_leases", "release_lease",
    "record_launch_intent", "mark_launched", "adopt_launch",
    "get_changelog", "apply_changelog", "changelog_span", "snapshot",
    "promote", "claim_config", "set_config", "get_config",
    "serve_traffic", "annotate_status", "find_cached_run",
})


def _receiver_src(expr: ast.AST) -> Optional[str]:
    """Source text of a receiver expression (``self._shards[i]``,
    ``home``, ...) for syntactic same-object comparison; None for
    receivers too dynamic to render (calls, comprehensions...)."""
    if isinstance(expr, (ast.Name, ast.Attribute, ast.Subscript)):
        try:
            return ast.unparse(expr)
        except Exception:
            return None
    return None


def _conn_ctx_receiver(expr: ast.AST) -> Optional[str]:
    """``X`` when ``expr`` is ``X._conn_ctx()``, else None."""
    if (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "_conn_ctx"):
        return _receiver_src(expr.func.value)
    return None


def _walk_same_context(node):
    """``node`` + descendants, excluding nested function/lambda/class
    bodies — a closure bound under the hold runs later (usually after
    release); flagging its calls would fabricate findings."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda, ast.ClassDef)):
        return
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _walk_same_context(child)


class CrossShardRule(Rule):
    name = "crossshard"
    title = "cross-shard store verb inside another shard's transaction"

    def check(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            for w in ast.walk(sf.tree):
                if not isinstance(w, ast.With):
                    continue
                holders = [_conn_ctx_receiver(item.context_expr)
                           for item in w.items]
                holders = [h for h in holders if h is not None]
                if not holders:
                    continue
                self._scan_hold(sf, w, holders, findings)
        return findings

    def _scan_hold(self, sf, w: ast.With, holders: list[str],
                   findings: list[Finding]) -> None:
        for stmt in w.body:
            for sub in _walk_same_context(stmt):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        inner = _conn_ctx_receiver(item.context_expr)
                        if inner is not None and inner not in holders:
                            findings.append(Finding(
                                rule=self.name, path=sf.rel,
                                line=sub.lineno,
                                message=(
                                    f"nested {inner}._conn_ctx() while "
                                    f"holding {holders[0]}'s transaction"
                                    " — one shard's writer lock held "
                                    "while opening another's (deadlock "
                                    "order hazard; finish or never "
                                    "start the outer transaction "
                                    "first)"),
                            ))
                elif (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in STORE_VERBS):
                    recv = _receiver_src(sub.func.value)
                    if recv is None or recv in holders:
                        continue
                    findings.append(Finding(
                        rule=self.name, path=sf.rel, line=sub.lineno,
                        message=(
                            f"store verb {recv}.{sub.func.attr}() "
                            f"inside {holders[0]}'s transaction scope — "
                            "the verb opens its own transaction under "
                            "the held shard's writer lock; route the "
                            "call outside the hold (per-shard "
                            "sub-batches open exactly one backend's "
                            "transaction)"),
                    ))
