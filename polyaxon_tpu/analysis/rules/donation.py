"""R6 — donation safety: a donated buffer is dead after the call.

Bug-class provenance (PR 8's trainer rollback work): the train step is
``jax.jit(step_fn, donate_argnums=(0,))`` — the old ``TrainState``'s
buffers are donated to the new one, and on TPU reading the donated
array afterwards returns garbage (or raises under
``jax_debug_nans``-style configs) — CPU tests pass because XLA:CPU may
decline the donation, which is what makes the class survive review.
The divergence-rollback path had to be written carefully so the
pre-step state needed for the in-jit select lives INSIDE the jitted
function; this rule keeps anyone from re-introducing a host-side read
of the donated argument.

Detection, project-wide:

- donating callables: ``X = jax.jit(f, donate_argnums=...)`` records
  both ``X`` and ``f``; a ``@partial(jax.jit, donate_argnums=...)``
  decorator records the decorated function's name (donated indices from
  the literal int/tuple);
- at every call of a recorded name: for each donated positional arg
  that is a plain variable, any later *read* of that variable in the
  same function body — before a rebinding — is a finding. A call whose
  own assignment rebinds the variable (``state, m = step(state, b)``)
  is the sanctioned idiom and starts the name clean.

Names are matched per terminal identifier (``self._compiled_step(...)``
matches a recorded ``_compiled_step``), which is deliberately
conservative-in-scope: a same-named non-donating function elsewhere
would need an inline ``plx: allow(donation)`` — cheap, explicit, and
much better than missing a real donation bug.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..engine import Finding, Project, Rule, dotted_name


def _donate_indices(call: ast.Call) -> Optional[tuple]:
    """The literal donate_argnums of a jax.jit(...) call, else None."""
    fn = dotted_name(call.func) or ""
    if fn.rsplit(".", 1)[-1] != "jit":
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except ValueError:
                return None
            if isinstance(v, int):
                return (v,)
            if isinstance(v, (tuple, list)):
                return tuple(int(i) for i in v)
    return None


def _partial_jit_donations(deco: ast.AST,
                           fn_node: ast.AST) -> Optional[tuple]:
    """Donated positional indices from a ``partial(jax.jit,
    donate_argnums=...)`` / ``donate_argnames=...`` decorator —
    argnames are resolved against the decorated function's signature
    (the serve decode/prefill form)."""
    if not isinstance(deco, ast.Call):
        return None
    fn = dotted_name(deco.func) or ""
    if fn.rsplit(".", 1)[-1] != "partial":
        return None
    if not deco.args:
        return None
    head = dotted_name(deco.args[0]) or ""
    if head.rsplit(".", 1)[-1] != "jit":
        return None
    for kw in deco.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except ValueError:
                return None
            return (v,) if isinstance(v, int) else tuple(v)
        if kw.arg == "donate_argnames":
            try:
                names = ast.literal_eval(kw.value)
            except ValueError:
                return None
            if isinstance(names, str):
                names = (names,)
            params = [a.arg for a in (fn_node.args.posonlyargs
                                      + fn_node.args.args)]
            return tuple(params.index(n) for n in names
                         if n in params) or None
    return None


def _collect_donating_names(project: Project) -> dict[str, tuple]:
    """terminal identifier -> donated positional indices."""
    out: dict[str, tuple] = {}
    for sf in project.files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                idx = _donate_indices(node.value)
                if idx is None:
                    continue
                for t in node.targets:
                    name = dotted_name(t)
                    if name is not None:
                        out[name.rsplit(".", 1)[-1]] = idx
                # the wrapped function is donating too (it may be called
                # under its own name after being jitted in place)
                if node.value.args:
                    wrapped = dotted_name(node.value.args[0])
                    if wrapped is not None:
                        out[wrapped.rsplit(".", 1)[-1]] = idx
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    idx = _partial_jit_donations(deco, node)
                    if idx is not None:
                        out[node.name] = idx
    return out


def _pos(node: ast.AST) -> tuple:
    return (node.lineno, node.col_offset)


def _stmt_chain(fn: ast.AST, target: ast.AST):
    """The chain of (stmt_list, index) locating the statement containing
    ``target`` at every nesting level of ``fn``'s body — the structural
    'what executes after this call' input. None when not found."""
    chain: list = []

    def contains(n) -> bool:
        return any(sub is target for sub in ast.walk(n))

    def blocks_of(stmt):
        for _, value in ast.iter_fields(stmt):
            if isinstance(value, list) and value:
                if isinstance(value[0], ast.stmt):
                    yield value
                elif isinstance(value[0], ast.excepthandler):
                    for h in value:
                        yield h.body

    def descend(body) -> bool:
        for i, stmt in enumerate(body):
            if not contains(stmt):
                continue
            chain.append((body, i, stmt))
            for blk in blocks_of(stmt):
                if descend(blk):
                    break
            return True
        return False

    return chain if descend(list(fn.body)) else None


class _NameUse(ast.NodeVisitor):
    """All (position, ctx) uses of one variable name in a function."""

    def __init__(self, name: str):
        self.name = name
        self.loads: list[tuple] = []
        self.stores: list[tuple] = []

    def visit_Name(self, node: ast.Name):
        if node.id == self.name:
            if isinstance(node.ctx, ast.Load):
                self.loads.append(_pos(node))
            else:
                self.stores.append(_pos(node))

    def visit_FunctionDef(self, node):
        return  # a nested scope's name is a different variable

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


class DonationRule(Rule):
    name = "donation"
    title = "donated jit buffers must not be read after the call"

    def check(self, project: Project) -> list[Finding]:
        donating = _collect_donating_names(project)
        if not donating:
            return []
        out: list[Finding] = []
        for sf in project.files:
            if sf.tree is None:
                continue
            for fn in ast.walk(sf.tree):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._check_function(sf, fn, donating, out)
        return out

    def _check_function(self, sf, fn, donating, out) -> None:
        # names rebound by the statement that CONTAINS each call — the
        # sanctioned `state, m = step(state, b)` idiom rebinds the donated
        # name at the call itself and starts it clean
        calls = []
        for stmt in ast.walk(fn):
            if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign, ast.Expr)):
                continue
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            rebound = set()
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        rebound.add(sub.id)
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is None:
                    continue
                idx = donating.get(name.rsplit(".", 1)[-1])
                if idx is not None:
                    calls.append((node, name, idx, rebound))
        # donating calls outside assignment/expression statements
        # (return / if / while headers): no rebinding at the call
        seen = {id(c) for c, *_ in calls}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and id(node) not in seen:
                name = dotted_name(node.func)
                if name is None:
                    continue
                idx = donating.get(name.rsplit(".", 1)[-1])
                if idx is not None:
                    calls.append((node, name, idx, set()))
        for call, cname, indices, rebound_at_call in calls:
            chain = _stmt_chain(fn, call)
            if chain is None:
                continue
            # a call under return/raise has no same-path code after it —
            # sibling statements that FOLLOW textually run only on paths
            # that never executed the donation
            if any(isinstance(stmt, (ast.Return, ast.Raise))
                   for _, _, stmt in chain):
                continue
            # statements that structurally execute after the call: the
            # suffix of every enclosing block. A read in a MUTUALLY
            # EXCLUSIVE branch (the else of the call's if) is not after
            # the call and must not be flagged.
            following = [s for body, i, _ in chain for s in body[i + 1:]]
            for i in indices:
                if i >= len(call.args):
                    continue
                arg = call.args[i]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in rebound_at_call:
                    continue  # rebound by the call's own assignment
                uses = _NameUse(arg.id)
                for stmt in following:
                    uses.visit(stmt)
                # plus the tail of the call's own statement (an
                # expression reading the name after the call inline)
                intra = _NameUse(arg.id)
                intra.visit(chain[-1][2])
                call_end = (call.end_lineno, call.end_col_offset)
                uses.loads.extend(p for p in intra.loads if p > call_end)
                rebinds = [p for p in uses.stores if p > call_end]
                horizon = min(rebinds) if rebinds else None
                for load in sorted(set(uses.loads)):
                    if load <= call_end:
                        continue
                    if horizon is not None and load > horizon:
                        break
                    out.append(Finding(
                        rule=self.name, path=sf.rel, line=load[0],
                        col=load[1],
                        message=(
                            f"use of {arg.id!r} after it was donated to "
                            f"{cname}() (donate_argnums includes {i}): "
                            "the buffer is invalidated by XLA donation — "
                            "read it before the call or thread it through "
                            "the jitted function"),
                    ))
        return
