"""CLI: ``python -m polyaxon_tpu.analysis [--json] [--root DIR]
[--rule NAME ...] [TARGET ...]``.

Exit code 0 iff the analyzed tree has no unsuppressed findings (the
contract scripts/ci.sh and the tier-1 tree-clean test gate on).
"""

from __future__ import annotations

import argparse
import json
import sys

from .engine import DEFAULT_TARGETS, default_rules, run_analysis


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "polyaxon_tpu.analysis",
        description="concurrency-invariant static analyzer "
                    "(rule catalog: docs/ANALYSIS.md)")
    p.add_argument("targets", nargs="*",
                   help=f"files/dirs relative to --root "
                        f"(default: {' '.join(DEFAULT_TARGETS)})")
    p.add_argument("--root", default=None,
                   help="analysis root (default: the repo root)")
    p.add_argument("--rule", action="append", default=None,
                   help="run only this rule (repeatable)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON on stdout")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:12s} {r.title}")
        return 0
    if args.rule:
        known = {r.name for r in rules}
        unknown = set(args.rule) - known
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in set(args.rule)]

    report = run_analysis(root=args.root, targets=args.targets or None,
                          rules=rules)
    if report.files_analyzed == 0:
        # a typo'd --root/target must not read as "clean" to a CI gate
        print(f"no Python files found under {report.root!r} "
              f"(targets: {args.targets or list(DEFAULT_TARGETS)})",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
