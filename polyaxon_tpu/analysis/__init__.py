"""Concurrency-invariant analysis (ISSUE 11).

Static half: ``python -m polyaxon_tpu.analysis`` runs the AST rule
suite over the live tree (see docs/ANALYSIS.md for the rule catalog and
suppression syntax). Runtime half: :class:`LockWitness` records actual
cross-thread lock-acquisition orders during the chaos soaks
(``scripts/chaos_soak.py --lock-witness``).
"""

from .engine import (Finding, Project, Report, Rule, run_analysis,
                     load_project, repo_root)
from .lockwitness import LockWitness, WitnessedLock

__all__ = [
    "Finding", "Project", "Report", "Rule", "run_analysis",
    "load_project", "repo_root", "LockWitness", "WitnessedLock",
]
