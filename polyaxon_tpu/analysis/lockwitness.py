"""Runtime lock-order witness (lockdep-style) — the dynamic complement
to the static ``lockorder`` rule.

The static rule can't see through dynamic dispatch (FencedStore's
``__getattr__`` proxying, callbacks stored in variables), so its graph
is an under-approximation. :class:`LockWitness` wraps the control
plane's real locks and records every cross-thread acquisition ORDER
actually taken while the chaos soaks run: acquiring ``B`` while holding
``A`` adds the edge ``A -> B``. A cycle in the witnessed graph is a
latent deadlock the soak merely got lucky on — ``chaos_soak.py
--lock-witness`` fails the soak on one, and dumps the witnessed orders
into ``bench_artifacts/`` next to the metrics scrapes.

Locks are witnessed by ROLE (``LocalAgent._lock``), not by instance:
lock-order discipline is a property of the code paths, so two agents'
loop locks share a node and a fleet soak accumulates one class-level
graph. Reentrant re-acquisition of the same role by the same thread is
not an edge (RLocks are legal to re-take).

Overhead is one thread-local list append plus, for new edges only, a
short critical section — negligible next to the soak's sleeps.
"""

from __future__ import annotations

import json
import os
import threading
import traceback
from typing import Optional


def _site(skip_frames: int = 3) -> str:
    """Compact "file:line (func)" of the acquiring frame, skipping the
    witness's own frames."""
    for frame in reversed(traceback.extract_stack()[:-skip_frames]):
        fn = frame.filename.replace("\\", "/")
        if "/analysis/lockwitness" in fn:
            continue
        short = "/".join(fn.rsplit("/", 2)[-2:])
        return f"{short}:{frame.lineno} ({frame.name})"
    return "?"


class WitnessedLock:
    """Duck-typed stand-in for threading.Lock/RLock that reports every
    acquisition order to its witness."""

    def __init__(self, inner, name: str, witness: "LockWitness"):
        self._inner = inner
        self._name = name
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._acquired(self._name)
        return got

    def release(self) -> None:
        self._witness._released(self._name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class LockWitness:
    """Cross-thread lock-acquisition-order recorder + cycle detector."""

    def __init__(self):
        self._tls = threading.local()
        self._meta = threading.Lock()
        # (held, acquired) -> {"count": n, "site": first-site}
        self._edges: dict[tuple, dict] = {}
        self._names: set = set()

    # -- recording ---------------------------------------------------------

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _acquired(self, name: str) -> None:
        held = self._held()
        is_new = name not in self._names or any(
            h != name and (h, name) not in self._edges for h in held)
        site = _site() if is_new else None
        with self._meta:
            self._names.add(name)
            for h in held:
                if h == name:
                    continue  # reentrant re-take of the same role
                entry = self._edges.setdefault(
                    (h, name), {"count": 0, "site": site or _site()})
                entry["count"] += 1
        held.append(name)

    def _released(self, name: str) -> None:
        held = self._held()
        # release the most recent hold of this role (locks nest)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    # -- instrumentation ---------------------------------------------------

    def wrap(self, lock, name: str) -> WitnessedLock:
        if isinstance(lock, WitnessedLock):
            return lock  # idempotent across agent restarts in one soak
        return WitnessedLock(lock, name, self)

    def instrument(self, obj, role: Optional[str] = None,
                   attrs: Optional[list] = None) -> None:
        """Replace ``obj``'s lock attributes with witnessed wrappers.
        Default attrs: every ``_*lock*`` attribute holding an acquirable
        object. Must run before the object's threads start."""
        role = role or type(obj).__name__
        names = attrs if attrs is not None else [
            a for a in vars(obj)
            if "lock" in a.lower() and hasattr(getattr(obj, a), "acquire")]
        for attr in names:
            lock = getattr(obj, attr, None)
            if lock is None or not hasattr(lock, "acquire"):
                continue
            setattr(obj, attr, self.wrap(lock, f"{role}.{attr}"))

    def instrument_control_plane(self, *, store=None, agent=None) -> None:
        """The curated control-plane lock set the soaks witness: the
        store's writer + heartbeat-fold locks, the agent's loop + dirty
        locks, and the reconciler's tracking + reconcile locks."""
        if store is not None:
            self.instrument(
                store, role="Store",
                attrs=["_transition_lock", "_train_lock", "_memory_lock"])
        if agent is not None:
            self.instrument(agent, role="LocalAgent",
                            attrs=["_lock", "_dirty_lock"])
            rec = getattr(agent, "reconciler", None)
            if rec is not None:
                self.instrument(
                    rec, role="OperationReconciler",
                    attrs=["_lock", "_reconcile_lock"])

    # -- verdicts ----------------------------------------------------------

    def edges(self) -> list[dict]:
        with self._meta:
            return [
                {"from": a, "to": b, "count": e["count"],
                 "first_site": e["site"]}
                for (a, b), e in sorted(self._edges.items())]

    def cycles(self) -> list[list]:
        """Every distinct cycle in the witnessed order graph (each a
        closed [a, b, ..., a] node list)."""
        from .engine import find_cycles

        with self._meta:
            graph: dict[str, set] = {}
            for a, b in self._edges:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        return find_cycles(graph)

    def report(self) -> dict:
        cycles = self.cycles()
        return {
            "locks": sorted(self._names),
            "edges": self.edges(),
            "cycles": cycles,
            "ok": not cycles,
        }

    def assert_no_cycles(self) -> None:
        cycles = self.cycles()
        if cycles:
            raise AssertionError(
                "witnessed lock-order cycle(s): "
                + "; ".join(" -> ".join(c) for c in cycles))

    def dump(self, path: str) -> dict:
        report = self.report()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        return report
