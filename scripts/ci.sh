#!/usr/bin/env bash
# Single-entry local CI gate (ISSUE 11 satellite): the concurrency
# analyzer, the partition rule-coverage audit (ISSUE 13 satellite), then
# the tier-1 pytest suite — exactly what ROADMAP.md's "Tier-1 verify"
# runs, so one command answers "is the tree shippable".
#
# Usage:
#   scripts/ci.sh            # analyzer + partition audit + tier-1 tests
#   scripts/ci.sh --fast     # analyzer + audit only (no pytest)
#
# Exit code: non-zero iff either gate fails. Caveat for slow boxes: on a
# 2-CPU container the tier-1 suite can exceed the 870s window by design
# (the driver's bar there is DOTS_PASSED, not the exit code). When the
# run is killed by the timeout (rc 124), set CI_DOTS_FLOOR=<n> to accept
# DOTS_PASSED >= n as a pass; otherwise 124 propagates with a warning.

set -o pipefail
cd "$(dirname "$0")/.."

echo "== gate 1/3: concurrency invariant analyzer =="
python -m polyaxon_tpu.analysis || exit 1

echo "== gate 2/3: partition rule-coverage audit =="
# every built-in model's full param tree must be matched by its shipped
# partition rule set, with legacy logical-axis spec parity — a model edit
# can't silently fall back to replicated sharding (docs/PARTITIONING.md)
env JAX_PLATFORMS=cpu python -m polyaxon_tpu.partition || exit 1

if [ "$1" = "--fast" ]; then
    echo "== --fast: skipping tier-1 pytest =="
    exit 0
fi

# gate 3 carries the perf regression smokes too: sched_bench's saturated
# burst (tests/test_sched_bench.py), dashboard_bench's SSE fan-out
# p95 bound (tests/test_dashboard_bench.py, ISSUE 14), and the tenancy
# fairness smoke + suite (tests/test_tenancy.py, sched_bench --tenants,
# ISSUE 15) all run as ordinary tier-1 tests — a change that hands the
# scheduler win back to polling, regresses publish->deliver latency, or
# breaks quota-proportional fairness fails this gate.
echo "== gate 3/3: tier-1 tests (ROADMAP.md verify) =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo "DOTS_PASSED=$dots"
if [ "$rc" = 124 ]; then
    if [ -n "$CI_DOTS_FLOOR" ] && [ "$dots" -ge "$CI_DOTS_FLOOR" ]; then
        echo "tier-1 hit the 870s window (expected on slow boxes);" \
             "DOTS_PASSED=$dots >= CI_DOTS_FLOOR=$CI_DOTS_FLOOR -> pass"
        exit 0
    fi
    echo "tier-1 hit the 870s window before finishing; the driver's bar" \
         "on slow boxes is DOTS_PASSED (set CI_DOTS_FLOOR to gate on it)"
fi
exit $rc
