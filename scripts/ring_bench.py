"""Ring-attention perf evidence (VERDICT r4 "what's weak" #3 / next #3).

One real chip means ring attention's multi-device behavior can't be
wall-clock-measured on hardware, so this script produces the two honest
artifacts this harness allows:

1. **Virtual-mesh timing** — ring vs the dense-gather strawman
   (all_gather the full K/V onto every device, run one local flash pass)
   on the 8-device CPU mesh, fwd+bwd, identical math. CPU wall time is not
   TPU wall time, but the *relative* cost of the two schedules at equal
   arithmetic shows the ring schedule is not pathologically overheaded,
   and the dense-gather peak-memory column shows why ring exists at all
   (full-KV residency vs one visiting chunk).

2. **Analytic v5e compute/comm ratio** — per ring step each device
   computes blockwise attention against the visiting chunk
   (4*b*n*s_l^2*d fwd FLOPs at full causal occupancy, half at the causal
   average) while ppermuting the next K/V chunk (2*2*b*n*s_l*d bytes of
   bf16). The ratio of MXU time to ICI time at v5e peak numbers
   (197 bf16 TFLOP/s, ~186 GB/s/link ICI) says whether XLA's
   latency-hiding scheduler CAN overlap the ring: ratio >> 1 means
   compute covers the transfer.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/ring_bench.py [--seq 8192,16384] [--cp 8]
Prints one JSON line per (seq, path).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_BF16_FLOPS = 197e12
V5E_ICI_BYTES_PER_S = 186e9  # per link, one direction


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from polyaxon_tpu.ops import ring_attention
    from polyaxon_tpu.ops.flash_attention import _flash_fwd
    from polyaxon_tpu.parallel import build_mesh
    from polyaxon_tpu.parallel.compat import shard_map

    seqs = [8192, 16384]
    if "--seq" in sys.argv:
        seqs = [int(s) for s in sys.argv[sys.argv.index("--seq") + 1].split(",")]
    cp = int(sys.argv[sys.argv.index("--cp") + 1]) if "--cp" in sys.argv else 8
    b, n, d = 1, 4, 64
    block = 512

    mesh = build_mesh({"context": cp})
    spec = P(None, None, "context", None)

    def run_path(fn, q, k, v, steps=3):
        # forward-only on both paths: both run the same _flash_fwd kernel
        # in interpret mode, so fwd-vs-fwd is the apples-to-apples
        # schedule comparison (the bwd rides the same ring — measured
        # equivalent in tests/test_ops_attention.py grads parity)
        jfn = jax.jit(fn)
        out = jfn(q, k, v)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = jfn(q, k, v)
            jax.block_until_ready(out)
            # device->host fetch: block_until_ready alone can return early
            # on some platforms (verify-skill note)
            float(out.reshape(-1)[0])
        return (time.perf_counter() - t0) / steps * 1000.0

    for s in seqs:
        key = jax.random.PRNGKey(0)
        qkv = [
            jax.random.normal(k_, (b, n, s, d), jnp.float32) * 0.1
            for k_ in jax.random.split(key, 3)
        ]

        @functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=(spec,) * 3, out_specs=spec,
        )
        def ring(q, k, v):
            return ring_attention(
                q, k, v, axis_name="context", axis_size=cp, causal=True,
                block_q=block, block_k=block, interpret=True)

        @functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=(spec,) * 3, out_specs=spec,
        )
        def gather(q, k, v):
            # the strawman ring replaces: materialize ALL of K/V on every
            # device, one flash pass with this shard's global row offset
            kf = jax.lax.all_gather(k, "context", axis=2, tiled=True)
            vf = jax.lax.all_gather(v, "context", axis=2, tiled=True)
            bq, nq, sl, dq = q.shape
            my = jax.lax.axis_index("context")
            o, _ = _flash_fwd(
                q.reshape(bq * nq, sl, dq), kf.reshape(bq * nq, s, dq),
                vf.reshape(bq * nq, s, dq), my * sl, 0,
                sm_scale=dq ** -0.5, causal=True,
                block_q=block, block_k=block, interpret=True)
            return o.reshape(bq, nq, sl, dq).astype(q.dtype)

        ring_ms = run_path(ring, *qkv)
        gather_ms = run_path(gather, *qkv)

        s_l = s // cp
        # per-step analytics at the flagship shapes (llama-1b: 32 q heads,
        # 4 kv heads, d=64), causal average (half the chunk pairs are fully
        # future and skipped). Comm counts the COMPACT kv chunk — the r5
        # ring ships kv heads and expands per visit, an 8x ICI cut on
        # these shapes vs shipping q-head-expanded chunks.
        nq, nkv, dm = 32, 4, 64
        step_flops = 4 * 1 * nq * s_l * s_l * dm * 0.5
        step_bytes = 2 * 2 * 1 * nkv * s_l * dm  # k+v, bf16, compact
        compute_s = step_flops / V5E_BF16_FLOPS
        comm_s = step_bytes / V5E_ICI_BYTES_PER_S
        kv_full_mb = 2 * 2 * b * n * s * d / 1e6
        kv_chunk_mb = kv_full_mb / cp
        print(json.dumps({
            "seq": s, "cp": cp, "b": b, "heads": n, "head_dim": d,
            "ring_fwd_ms": round(ring_ms, 1),
            "gather_fwd_ms": round(gather_ms, 1),
            "ring_over_gather": round(ring_ms / gather_ms, 2),
            "kv_resident_mb_ring": round(kv_chunk_mb, 2),
            "kv_resident_mb_gather": round(kv_full_mb, 2),
            "v5e_step_compute_us_llama1b": round(compute_s * 1e6, 1),
            "v5e_step_comm_us_llama1b_gqa_compact": round(comm_s * 1e6, 1),
            "v5e_compute_over_comm": round(compute_s / comm_s, 1),
        }))


if __name__ == "__main__":
    main()
