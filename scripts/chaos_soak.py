"""Manual chaos soak driver (docs/RESILIENCE.md).

Drives a DAG + a grid matrix sweep through the full agent/operator stack
while a seed-driven fault schedule injects cluster API 5xx/429/timeouts
and pod preemptions, then compares every run's terminal status against a
fault-free oracle pass. Exit code 0 iff the chaotic pass converges to the
oracle.

Usage:
    JAX_PLATFORMS=cpu python scripts/chaos_soak.py \
        [--seed 2024] [--fault-rate 0.08] [--timeout-rate 0.02] \
        [--preempt-rate 0.03] [--max-preemptions 2] [--trials 3] \
        [--rounds 1] [--keep]

Every knob maps 1:1 onto ChaosConfig; --rounds repeats the chaotic pass
with seed, seed+1, ... for endurance sweeps. The pytest-integrated proofs
live in tests/test_chaos_soak.py (slow) and tests/test_resilience.py
(tier-1 smoke).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _specs(trials: int):
    from polyaxon_tpu.polyaxonfile import check_polyaxonfile

    write_out = (
        "import json, os; "
        "json.dump({'x': %s}, open(os.path.join("
        "os.environ['PLX_ARTIFACTS_PATH'], 'outputs.json'), 'w'))"
    )

    def job(cmd):
        return {"kind": "component",
                "run": {"kind": "job",
                        "container": {"command": [sys.executable, "-c", cmd]}}}

    dag = check_polyaxonfile({
        "kind": "operation",
        "name": "soak-dag",
        "component": {"kind": "component", "run": {"kind": "dag", "operations": [
            {"kind": "operation", "name": "prep",
             "termination": {"maxRetries": 3}, "component": job(write_out % "13")},
            {"kind": "operation", "name": "tail",
             "termination": {"maxRetries": 3}, "component": job(write_out % "1"),
             "dependencies": ["prep"]},
        ]}},
    }).to_dict()
    sweep = check_polyaxonfile({
        "kind": "operation",
        "name": "soak-sweep",
        "termination": {"maxRetries": 3},
        "matrix": {"kind": "grid", "concurrency": 2,
                   "params": {"x": {"kind": "choice",
                                    "value": list(range(1, trials + 1))}}},
        "component": {
            "kind": "component",
            "inputs": [{"name": "x", "type": "int"}],
            "run": {"kind": "job", "container": {"command": [
                sys.executable, "-c",
                "import json, os; "
                "x = int(json.loads(os.environ['PLX_PARAMS'])['x']); "
                "json.dump({'loss': float(x)}, open(os.path.join("
                "os.environ['PLX_ARTIFACTS_PATH'], 'outputs.json'), 'w'))",
            ]}},
        },
    }).to_dict()
    return [dag, sweep]


def _pass(workdir: str, trials: int, chaos_cfg=None, timeout: float = 600.0):
    from polyaxon_tpu.api.store import Store
    from polyaxon_tpu.operator import FakeCluster
    from polyaxon_tpu.resilience import ChaosCluster
    from polyaxon_tpu.scheduler.agent import LocalAgent

    store = Store(":memory:")
    cluster = FakeCluster(os.path.join(workdir, ".cluster"))
    if chaos_cfg is not None:
        cluster = ChaosCluster(cluster, chaos_cfg)
    agent = LocalAgent(store, workdir, backend="cluster", cluster=cluster,
                       poll_interval=0.05)
    agent.start()
    try:
        uuids = [store.create_run("p", spec=s, name=s.get("name"))["uuid"]
                 for s in _specs(trials)]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rows = [store.get_run(u) for u in uuids]
            if all(r["status"] in ("succeeded", "failed", "stopped")
                   for r in rows):
                break
            time.sleep(0.2)
        statuses = {}
        for row in store.list_runs(limit=500):
            statuses[row["name"]] = row["status"]
        injected = list(getattr(cluster, "injected", []))
        return statuses, injected
    finally:
        agent.stop()


def main() -> int:
    p = argparse.ArgumentParser("chaos_soak", description=__doc__)
    p.add_argument("--seed", type=int, default=2024)
    p.add_argument("--fault-rate", type=float, default=0.08,
                   help="per-verb probability of an injected API 5xx/429")
    p.add_argument("--timeout-rate", type=float, default=0.02)
    p.add_argument("--preempt-rate", type=float, default=0.03)
    p.add_argument("--max-api-faults", type=int, default=12)
    p.add_argument("--max-preemptions", type=int, default=2)
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--rounds", type=int, default=1)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--keep", action="store_true",
                   help="keep the scratch workdir for inspection")
    args = p.parse_args()

    from polyaxon_tpu.resilience import ChaosConfig

    root = tempfile.mkdtemp(prefix="plx-chaos-soak-")
    ok = True
    try:
        oracle, _ = _pass(os.path.join(root, "oracle"), args.trials,
                          timeout=args.timeout)
        print(json.dumps({"pass": "oracle", "statuses": oracle}))
        if any(v != "succeeded" for v in oracle.values()):
            print(json.dumps({"error": "oracle pass did not fully succeed"}))
            return 2
        for i in range(args.rounds):
            seed = args.seed + i
            cfg = ChaosConfig(
                seed=seed, api_fault_rate=args.fault_rate,
                timeout_rate=args.timeout_rate,
                preempt_rate=args.preempt_rate,
                max_api_faults=args.max_api_faults,
                max_preemptions=args.max_preemptions,
            )
            statuses, injected = _pass(
                os.path.join(root, f"chaos-{seed}"), args.trials, cfg,
                timeout=args.timeout)
            converged = statuses == oracle
            ok = ok and converged
            print(json.dumps({
                "pass": f"chaos-{seed}",
                "converged": converged,
                "injected": len(injected),
                "injected_kinds": sorted({k for k, _ in injected}),
                "diff": {k: (oracle.get(k), statuses.get(k))
                         for k in set(oracle) | set(statuses)
                         if oracle.get(k) != statuses.get(k)},
            }))
    finally:
        if args.keep:
            print(json.dumps({"workdir": root}))
        else:
            shutil.rmtree(root, ignore_errors=True)
    print(json.dumps({"ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
